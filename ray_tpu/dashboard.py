"""Cluster dashboard: HTTP JSON API + single-page UI over live state.

Role-equivalent of ray: dashboard/head.py:81 + dashboard/modules/
{node,actor,job,metrics,state}/ — collapsed into one aiohttp server fed
directly from the GCS tables through the state API, instead of a head
process + per-node agents + React build.  The UI is a self-contained
HTML page (no build step) polling the JSON endpoints.

Endpoints::

    GET /                       single-page UI
    GET /healthz                liveness probe
    GET /api/summary            cluster summary (ray status analogue)
    GET /api/nodes|actors|tasks|workers|objects|placement_groups
    GET /api/jobs               submitted jobs (job_submission)
    GET /api/metrics            aggregated Counter/Gauge/Histogram points
    GET /api/timeline           chrome-trace events
    GET /api/logs               log files in this node's session dir
    GET /api/logs/{name}?lines=N   tail one log file

Logs are served from the dashboard node's own session dir; in this
repo's single-host test topology every raylet shares the host, so all
worker logs are visible.  (A per-node log RPC is the multi-host
extension point, like the reference's dashboard agents.)
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

import ray_tpu

logger = logging.getLogger(__name__)

DASHBOARD_NAME = "_rt_dashboard"

_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.4rem}
 table{border-collapse:collapse;width:100%;background:#fff}
 th,td{border:1px solid #ddd;padding:4px 8px;font-size:.85rem;text-align:left}
 th{background:#f0f0f0} pre{background:#fff;border:1px solid #ddd;padding:8px}
 .pill{display:inline-block;padding:0 6px;border-radius:8px;background:#e8f0fe}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<div id="summary"></div>
<h2>Nodes</h2><div id="nodes"></div>
<h2>Actors</h2><div id="actors"></div>
<h2>Placement groups</h2><div id="placement_groups"></div>
<h2>Jobs</h2><div id="jobs"></div>
<h2>Metrics</h2><div id="metrics"></div>
<script>
function table(rows){
  if(!rows || !rows.length) return '<i>none</i>';
  const cols=[...new Set(rows.flatMap(r=>Object.keys(r)))];
  let h='<table><tr>'+cols.map(c=>'<th>'+c+'</th>').join('')+'</tr>';
  for(const r of rows) h+='<tr>'+cols.map(c=>'<td>'+
    (typeof r[c]==='object'?JSON.stringify(r[c]):String(r[c]??''))+'</td>').join('')+'</tr>';
  return h+'</table>';
}
async function refresh(){
  for(const name of ['nodes','actors','placement_groups','jobs','metrics']){
    try{const r=await fetch('/api/'+name);
        document.getElementById(name).innerHTML=table(await r.json());}catch(e){}
  }
  try{const s=await(await fetch('/api/summary')).json();
      document.getElementById('summary').innerHTML='<pre>'+JSON.stringify(s,null,1)+'</pre>';}catch(e){}
}
refresh(); setInterval(refresh, 5000);
</script></body></html>"""


def render_prometheus(metrics: list) -> str:
    """GCS metric aggregate → Prometheus text exposition format.

    Series keys are json-encoded sorted tag pairs with optional
    ``|le=...`` / ``|sum`` histogram suffixes; gauges are prefixed
    ``reporter|`` (kept as a `reporter` label so per-process values stay
    distinct under aggregation)."""
    import json as _json
    import re

    def sanitize(name: str) -> str:
        return re.sub(r"[^a-zA-Z0-9_:]", "_", name)

    def labels(tags_json: str, extra: dict) -> str:
        try:
            pairs = dict(tuple(p) for p in _json.loads(tags_json))
        except Exception:
            pairs = {}
        pairs.update(extra)
        if not pairs:
            return ""
        def esc(v) -> str:
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace("\n", "\\n")
                .replace('"', '\\"')
            )

        inner = ",".join(
            f'{sanitize(k)}="{esc(v)}"' for k, v in sorted(pairs.items())
        )
        return "{" + inner + "}"

    out = []
    for m in metrics:
        name = sanitize(m["name"])
        mtype = m["type"]
        if m.get("description"):
            desc = (
                str(m["description"])
                .replace("\\", "\\\\")
                .replace("\n", "\\n")
            )
            out.append(f"# HELP {name} {desc}")
        out.append(f"# TYPE {name} {mtype}")
        for key, value in sorted(m["series"].items()):
            extra = {}
            if mtype == "gauge" and "|" in key:
                reporter, key = key.split("|", 1)
                extra["reporter"] = reporter
            if mtype == "histogram":
                # suffixes are APPENDED after the json tags, so the LAST
                # '|' is the real separator (a '|' inside a tag value
                # must not split the key)
                tags_json, _, suffix = key.rpartition("|")
                if suffix.startswith("le="):
                    le = suffix[3:]
                    out.append(
                        f"{name}_bucket"
                        f"{labels(tags_json, {**extra, 'le': le})} {value}"
                    )
                elif suffix == "sum":
                    out.append(
                        f"{name}_sum{labels(tags_json, extra)} {value}"
                    )
                continue
            out.append(f"{name}{labels(key, extra)} {value}")
    # histogram _count = the +Inf bucket, emitted in a second pass
    for m in metrics:
        if m["type"] != "histogram":
            continue
        name = sanitize(m["name"])
        for key, value in sorted(m["series"].items()):
            if key.endswith("|le=+Inf"):
                tags_json = key.rsplit("|", 1)[0]
                out.append(f"{name}_count{labels(tags_json, {})} {value}")
    return "\n".join(out) + "\n"


@ray_tpu.remote
class DashboardActor:
    """Serves the dashboard; runs as a detached actor on the cluster."""

    def __init__(self, port: int = 8265, host: str = "127.0.0.1"):
        # localhost by default: the dashboard serves cluster state and log
        # file contents with no auth, so a network bind must be explicit
        # (matches the reference dashboard's default).
        self._port = port
        self._host = host
        self._runner = None

    async def start(self) -> int:
        from aiohttp import web

        if self._runner is not None:  # idempotent under get_if_exists reuse
            return self._port
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/api/summary", self._summary)
        for name in ("nodes", "actors", "tasks", "workers", "objects",
                     "placement_groups"):
            app.router.add_get(f"/api/{name}", self._make_list(name))
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/metrics", self._metrics)
        app.router.add_get("/metrics", self._metrics_prometheus)
        app.router.add_get("/api/profile/stacks", self._profile_stacks)
        app.router.add_get("/api/events", self._events)
        app.router.add_get("/api/timeline", self._timeline)
        app.router.add_get("/api/logs", self._logs_index)
        app.router.add_get("/api/logs/{name}", self._logs_tail)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        if self._port == 0:  # ephemeral: report the bound port
            for server in self._runner.sites:
                sock = server._server.sockets[0]
                self._port = sock.getsockname()[1]
                break
        return self._port

    def ping(self) -> bool:
        return True

    # -- handlers ------------------------------------------------------

    async def _index(self, req):
        from aiohttp import web

        return web.Response(text=_HTML, content_type="text/html")

    async def _healthz(self, req):
        from aiohttp import web

        return web.json_response({"ok": True})

    def _json(self, payload):
        from aiohttp import web

        return web.Response(
            text=json.dumps(payload, default=str),
            content_type="application/json",
        )

    async def _offload(self, fn):
        # state calls block on runtime._run, which posts to THIS actor's
        # event loop — run them in an executor thread or they deadlock
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(None, fn)

    async def _summary(self, req):
        from ray_tpu.util import state

        return self._json(await self._offload(state.summarize))

    def _make_list(self, name):
        async def handler(req):
            from ray_tpu.util import state

            fn = getattr(state, f"list_{name}")
            return self._json(await self._offload(fn))

        return handler

    async def _jobs(self, req):
        from ray_tpu.core.runtime import get_runtime

        def call():
            rt = get_runtime()
            return rt._run(rt.gcs.call("list_jobs", {}))

        return self._json(await self._offload(call))

    async def _metrics(self, req):
        from ray_tpu.util import state

        return self._json(await self._offload(state.get_metrics))

    async def _metrics_prometheus(self, req):
        """Prometheus text exposition of the GCS metric aggregate
        (reference role: the per-node metrics agent's /metrics endpoint,
        ray: dashboard/modules/reporter — here one scrape target for the
        cluster, point `prometheus.yml` at /metrics)."""
        from aiohttp import web

        from ray_tpu.util import state

        metrics = await self._offload(state.get_metrics)
        return web.Response(
            text=render_prometheus(metrics),
            content_type="text/plain",
            charset="utf-8",
        )

    async def _profile_stacks(self, req):
        """GET /api/profile/stacks?worker=<hex> — on-demand per-thread
        stacks of a live worker (py-spy role)."""
        from ray_tpu.util import state

        worker = req.query.get("worker", "")
        if not worker:
            return self._json({"error": "pass ?worker=<hex worker id>"})
        try:
            return self._json(
                await self._offload(lambda: state.worker_stacks(worker))
            )
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            return self._json({"error": repr(e)})

    async def _events(self, req):
        from ray_tpu.util import events

        sev = req.query.get("severity")

        def call():
            return events.list_events(severity=sev)

        return self._json(await self._offload(call))

    async def _timeline(self, req):
        return self._json(await self._offload(ray_tpu.timeline))

    def _session_dir(self) -> str:
        return os.environ.get("RT_SESSION_DIR", "/tmp/ray_tpu")

    async def _logs_index(self, req):
        d = self._session_dir()
        try:
            files = sorted(
                f for f in os.listdir(d) if f.endswith(".log")
            )
        except FileNotFoundError:
            files = []
        return self._json([{"name": f, "size": os.path.getsize(
            os.path.join(d, f))} for f in files])

    async def _logs_tail(self, req):
        from aiohttp import web

        name = req.match_info["name"]
        if "/" in name or ".." in name or not name.endswith(".log"):
            return web.Response(status=400, text="bad log name")
        path = os.path.join(self._session_dir(), name)
        if not os.path.exists(path):
            return web.Response(status=404, text="no such log")
        lines = int(req.query.get("lines", "200"))
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 256 * 1024))
            tail = f.read().decode("utf-8", "replace").splitlines()[-lines:]
        return web.Response(text="\n".join(tail), content_type="text/plain")


def start_dashboard(port: int = 8265, host: str = "127.0.0.1") -> str:
    """Start (or reuse) the cluster dashboard; returns its URL.

    ``host`` is the bind address on whichever node hosts the dashboard
    actor.  The localhost default is safe (no auth on the endpoints);
    multi-node operators who need remote access pass ``host="0.0.0.0"``
    explicitly and front it themselves.
    """
    actor = DashboardActor.options(
        name=DASHBOARD_NAME, get_if_exists=True, lifetime="detached",
        num_cpus=0.1,
    ).remote(port, host)
    bound = ray_tpu.get(actor.start.remote(), timeout=120)
    display = "127.0.0.1" if host in ("0.0.0.0", "127.0.0.1") else host
    return f"http://{display}:{bound}"


def stop_dashboard() -> None:
    try:
        actor = ray_tpu.get_actor(DASHBOARD_NAME)
        ray_tpu.kill(actor)
    except Exception:
        pass
