"""Cluster dashboard: HTTP JSON API + single-page UI over live state.

Role-equivalent of ray: dashboard/head.py:81 + dashboard/modules/
{node,actor,job,metrics,state}/ — collapsed into one aiohttp server fed
directly from the GCS tables through the state API, instead of a head
process + per-node agents + React build.  The UI is a self-contained
HTML page (no build step) polling the JSON endpoints.

Endpoints::

    GET /                       single-page UI
    GET /healthz                liveness probe
    GET /api/summary            cluster summary (ray status analogue)
    GET /api/nodes|actors|tasks|workers|objects|placement_groups
    GET /api/jobs               submitted jobs (job_submission)
    GET /api/metrics            aggregated Counter/Gauge/Histogram points
    GET /api/timeline           chrome-trace events
    GET /api/logs               log files in this node's session dir
    GET /api/logs/{name}?lines=N   tail one log file

Logs are served from the dashboard node's own session dir; in this
repo's single-host test topology every raylet shares the host, so all
worker logs are visible.  (A per-node log RPC is the multi-host
extension point, like the reference's dashboard agents.)
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

import ray_tpu

logger = logging.getLogger(__name__)

DASHBOARD_NAME = "_rt_dashboard"

#: (route-kind, nav label) for the per-subsystem HTML pages
_PAGE_KINDS = [
    ("nodes", "Nodes"),
    ("actors", "Actors"),
    ("tasks", "Tasks"),
    ("workers", "Workers"),
    ("objects", "Objects"),
    ("placement_groups", "Placement groups"),
    ("jobs", "Jobs"),
    ("metrics", "Metrics"),
    ("events", "Events"),
    ("logs", "Logs"),
]

_PAGE_CSS = """
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa}
 h1{font-size:1.3rem}
 table{border-collapse:collapse;width:100%;background:#fff}
 th,td{border:1px solid #ddd;padding:4px 8px;font-size:.85rem;text-align:left}
 th{background:#f0f0f0} pre{background:#fff;border:1px solid #ddd;padding:8px}
 nav a{margin-right:.8rem} nav a.active{font-weight:bold}
"""


def _render_table(rows, raw: bool = False) -> str:
    """Server-side twin of the index page's JS table(): union of keys as
    columns, values escaped (``raw=True`` only for server-built trusted
    cells like log links)."""
    import html as _html

    if isinstance(rows, dict):
        rows = [
            {"key": k, "value": v} for k, v in rows.items()
        ]
    if not rows:
        return "<i>none</i>"
    if not isinstance(rows, list) or not isinstance(rows[0], dict):
        return f"<pre>{_html.escape(json.dumps(rows, default=str, indent=1))}</pre>"
    cols = []
    for r in rows:
        for k in r:
            if k not in cols:
                cols.append(k)

    def cell(v):
        s = json.dumps(v, default=str) if isinstance(
            v, (dict, list)
        ) else ("" if v is None else str(v))
        return s if raw else _html.escape(s)

    out = ["<table><tr>"]
    out += [f"<th>{_html.escape(str(c))}</th>" for c in cols]
    out.append("</tr>")
    for r in rows:
        out.append("<tr>")
        out += [f"<td>{cell(r.get(c))}</td>" for c in cols]
        out.append("</tr>")
    out.append("</table>")
    return "".join(out)


def _render_page(title: str, active: str, content: str,
                 api: str = "", client_refresh: bool = False) -> str:
    """Page skeleton: shared nav + server-rendered content; when ``api``
    is set and refresh requested, the content re-renders client-side
    from the same JSON endpoint every 5 s."""
    import html as _html

    # NOTE: the class attr is built outside the f-string — a backslash
    # escape inside an f-string expression is a syntax error before 3.12
    active_attr = ' class="active"'
    nav = "".join(
        f'<a href="/{k}"{active_attr if k == active else ""}>'
        f"{label}</a>"
        for k, label in _PAGE_KINDS
    )
    refresh = ""
    if api and client_refresh:
        refresh = f"""<script>
{_TABLE_JS}
setInterval(async()=>{{
  try{{const r=await fetch('{api}');
      document.getElementById('content').innerHTML=table(await r.json());
  }}catch(e){{}}
}},5000);
</script>"""
    return f"""<!doctype html>
<html><head><meta charset="utf-8">
<title>ray_tpu — {_html.escape(title)}</title>
<style>{_PAGE_CSS}</style></head><body>
<h1>ray_tpu — {_html.escape(title)}</h1>
<nav><a href="/">Overview</a>{nav}</nav>
<div id="content">{content}</div>
{refresh}</body></html>"""


_TABLE_JS = """
function esc(s){const d=document.createElement('div');d.textContent=s;return d.innerHTML}
function table(rows){
  if(rows && !Array.isArray(rows)) rows=Object.entries(rows).map(([key,value])=>({key,value}));
  if(!rows || !rows.length) return '<i>none</i>';
  const cols=[...new Set(rows.flatMap(r=>Object.keys(r)))];
  let h='<table><tr>'+cols.map(c=>'<th>'+esc(c)+'</th>').join('')+'</tr>';
  for(const r of rows) h+='<tr>'+cols.map(c=>'<td>'+
    esc(typeof r[c]==='object'&&r[c]!==null?JSON.stringify(r[c]):String(r[c]??''))+'</td>').join('')+'</tr>';
  return h+'</table>';
}
"""


_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:#fafafa}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.4rem}
 table{border-collapse:collapse;width:100%;background:#fff}
 th,td{border:1px solid #ddd;padding:4px 8px;font-size:.85rem;text-align:left}
 th{background:#f0f0f0} pre{background:#fff;border:1px solid #ddd;padding:8px}
 .pill{display:inline-block;padding:0 6px;border-radius:8px;background:#e8f0fe}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<nav>{NAV}</nav>
<div id="summary"></div>
<h2>Nodes</h2><div id="nodes"></div>
<h2>Actors</h2><div id="actors"></div>
<h2>Placement groups</h2><div id="placement_groups"></div>
<h2>Jobs</h2><div id="jobs"></div>
<h2>Metrics</h2><div id="metrics"></div>
<script>
function table(rows){
  if(!rows || !rows.length) return '<i>none</i>';
  const cols=[...new Set(rows.flatMap(r=>Object.keys(r)))];
  let h='<table><tr>'+cols.map(c=>'<th>'+c+'</th>').join('')+'</tr>';
  for(const r of rows) h+='<tr>'+cols.map(c=>'<td>'+
    (typeof r[c]==='object'?JSON.stringify(r[c]):String(r[c]??''))+'</td>').join('')+'</tr>';
  return h+'</table>';
}
async function refresh(){
  for(const name of ['nodes','actors','placement_groups','jobs','metrics']){
    try{const r=await fetch('/api/'+name);
        document.getElementById(name).innerHTML=table(await r.json());}catch(e){}
  }
  try{const s=await(await fetch('/api/summary')).json();
      document.getElementById('summary').innerHTML='<pre>'+JSON.stringify(s,null,1)+'</pre>';}catch(e){}
}
refresh(); setInterval(refresh, 5000);
</script></body></html>"""

# one source of truth for the page list: the index nav is generated from
# _PAGE_KINDS exactly like every subsystem page's nav
_HTML = _HTML.replace(
    "{NAV}",
    "".join(
        f'<a href="/{k}">{label}</a>' for k, label in _PAGE_KINDS
    ),
)


def render_prometheus(metrics: list) -> str:
    """GCS metric aggregate → Prometheus text exposition format.

    Series keys are json-encoded sorted tag pairs with optional
    ``|le=...`` / ``|sum`` histogram suffixes; gauges are prefixed
    ``reporter|`` (kept as a `reporter` label so per-process values stay
    distinct under aggregation)."""
    import json as _json
    import re

    def sanitize(name: str) -> str:
        return re.sub(r"[^a-zA-Z0-9_:]", "_", name)

    def labels(tags_json: str, extra: dict) -> str:
        try:
            pairs = dict(tuple(p) for p in _json.loads(tags_json))
        except Exception:
            pairs = {}
        pairs.update(extra)
        if not pairs:
            return ""
        def esc(v) -> str:
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace("\n", "\\n")
                .replace('"', '\\"')
            )

        inner = ",".join(
            f'{sanitize(k)}="{esc(v)}"' for k, v in sorted(pairs.items())
        )
        return "{" + inner + "}"

    out = []
    for m in metrics:
        name = sanitize(m["name"])
        mtype = m["type"]
        if m.get("description"):
            desc = (
                str(m["description"])
                .replace("\\", "\\\\")
                .replace("\n", "\\n")
            )
            out.append(f"# HELP {name} {desc}")
        out.append(f"# TYPE {name} {mtype}")
        for key, value in sorted(m["series"].items()):
            extra = {}
            if mtype == "gauge" and "|" in key:
                reporter, key = key.split("|", 1)
                extra["reporter"] = reporter
            if mtype == "histogram":
                # suffixes are APPENDED after the json tags, so the LAST
                # '|' is the real separator (a '|' inside a tag value
                # must not split the key)
                tags_json, _, suffix = key.rpartition("|")
                if suffix.startswith("le="):
                    le = suffix[3:]
                    out.append(
                        f"{name}_bucket"
                        f"{labels(tags_json, {**extra, 'le': le})} {value}"
                    )
                elif suffix == "sum":
                    out.append(
                        f"{name}_sum{labels(tags_json, extra)} {value}"
                    )
                continue
            out.append(f"{name}{labels(key, extra)} {value}")
    # histogram _count = the +Inf bucket, emitted in a second pass
    for m in metrics:
        if m["type"] != "histogram":
            continue
        name = sanitize(m["name"])
        for key, value in sorted(m["series"].items()):
            if key.endswith("|le=+Inf"):
                tags_json = key.rsplit("|", 1)[0]
                out.append(f"{name}_count{labels(tags_json, {})} {value}")
    return "\n".join(out) + "\n"


@ray_tpu.remote
class DashboardActor:
    """Serves the dashboard; runs as a detached actor on the cluster."""

    def __init__(self, port: int = 8265, host: str = "127.0.0.1"):
        # localhost by default: the dashboard serves cluster state and log
        # file contents with no auth, so a network bind must be explicit
        # (matches the reference dashboard's default).
        self._port = port
        self._host = host
        self._runner = None

    async def start(self) -> int:
        from aiohttp import web

        if self._runner is not None:  # idempotent under get_if_exists reuse
            return self._port
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/api/summary", self._summary)
        for name in ("nodes", "actors", "tasks", "workers", "objects",
                     "placement_groups"):
            app.router.add_get(f"/api/{name}", self._make_list(name))
        app.router.add_get("/api/jobs", self._jobs)
        # REST job API (reference: dashboard/modules/job/job_head.py:273-380
        # JobHead) — external tooling/CI submits without the Python SDK;
        # thin handlers over the GCS job-manager RPCs
        app.router.add_post("/api/jobs/", self._job_submit)
        app.router.add_get("/api/jobs/{submission_id}", self._job_info)
        app.router.add_get(
            "/api/jobs/{submission_id}/logs", self._job_logs
        )
        app.router.add_post(
            "/api/jobs/{submission_id}/stop", self._job_stop
        )
        app.router.add_delete("/api/jobs/{submission_id}", self._job_delete)
        app.router.add_get("/api/metrics", self._metrics)
        app.router.add_get("/metrics", self._metrics_prometheus)
        app.router.add_get("/api/profile/stacks", self._profile_stacks)
        app.router.add_get("/api/events", self._events)
        app.router.add_get("/api/timeline", self._timeline)
        app.router.add_get("/api/logs", self._logs_index)
        app.router.add_get("/api/logs/{name}", self._logs_tail)
        # per-subsystem HTML pages (reference role: the dashboard UI's
        # pages — cluster/actors/jobs/..., here server-rendered tables).
        # /metrics stays the Prometheus endpoint for scrapers; browsers
        # get the HTML page via Accept-header negotiation there.
        for kind, label in _PAGE_KINDS:
            if kind == "metrics":
                continue
            app.router.add_get(
                f"/{kind}", self._make_html_page(kind, label)
            )
        app.router.add_get("/logs/{name}", self._log_page)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        if self._port == 0:  # ephemeral: report the bound port
            for server in self._runner.sites:
                sock = server._server.sockets[0]
                self._port = sock.getsockname()[1]
                break
        return self._port

    def ping(self) -> bool:
        return True

    # -- handlers ------------------------------------------------------

    async def _index(self, req):
        from aiohttp import web

        return web.Response(text=_HTML, content_type="text/html")

    async def _healthz(self, req):
        from aiohttp import web

        return web.json_response({"ok": True})

    def _json(self, payload):
        from aiohttp import web

        return web.Response(
            text=json.dumps(payload, default=str),
            content_type="application/json",
        )

    async def _offload(self, fn):
        # state calls block on runtime._run, which posts to THIS actor's
        # event loop — run them in an executor thread or they deadlock
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(None, fn)

    async def _summary(self, req):
        from ray_tpu.util import state

        return self._json(await self._offload(state.summarize))

    def _make_list(self, name):
        async def handler(req):
            from ray_tpu.util import state

            fn = getattr(state, f"list_{name}")
            return self._json(await self._offload(fn))

        return handler

    async def _jobs(self, req):
        return self._json(await self._page_rows("jobs"))

    # -- REST job API (ray: dashboard/modules/job/job_head.py:273-380) --

    def _gcs_call(self, method, payload):
        from ray_tpu.core.runtime import get_runtime

        def call():
            rt = get_runtime()
            return rt._run(rt.gcs.call(method, payload))

        return self._offload(call)

    async def _job_submit(self, req):
        from aiohttp import web

        try:
            body = await req.json()
        except Exception:
            return web.json_response(
                {"error": "body must be JSON"}, status=400
            )
        if not body.get("entrypoint"):
            return web.json_response(
                {"error": "entrypoint is required"}, status=400
            )
        payload = {
            "entrypoint": body["entrypoint"],
            "submission_id": body.get("submission_id"),
            "runtime_env": body.get("runtime_env"),
            "metadata": body.get("metadata", {}),
        }
        try:
            reply = await self._gcs_call("submit_job", payload)
        except Exception as e:  # duplicate id, bad runtime env, ...
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(
            {"submission_id": reply["submission_id"]}
        )

    async def _job_info(self, req):
        from aiohttp import web

        try:
            info = await self._gcs_call(
                "get_job_info",
                {"submission_id": req.match_info["submission_id"]},
            )
        except Exception:  # GCS raises for unknown submission ids
            return web.json_response({"error": "no such job"}, status=404)
        return self._json(info)

    async def _job_logs(self, req):
        from aiohttp import web

        try:
            logs = await self._gcs_call(
                "get_job_logs",
                {"submission_id": req.match_info["submission_id"]},
            )
        except Exception:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response({"logs": logs})

    async def _job_stop(self, req):
        from aiohttp import web

        ok = await self._gcs_call(
            "stop_job", {"submission_id": req.match_info["submission_id"]}
        )
        if not ok:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response({"stopped": True})

    async def _job_delete(self, req):
        from aiohttp import web

        try:
            ok = await self._gcs_call(
                "delete_job",
                {"submission_id": req.match_info["submission_id"]},
            )
        except Exception as e:  # still RUNNING
            return web.json_response({"error": str(e)}, status=400)
        if not ok:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response({"deleted": True})

    async def _metrics(self, req):
        return self._json(await self._page_rows("metrics"))

    async def _metrics_prometheus(self, req):
        """Prometheus text exposition of the GCS metric aggregate
        (reference role: the per-node metrics agent's /metrics endpoint,
        ray: dashboard/modules/reporter — here one scrape target for the
        cluster, point `prometheus.yml` at /metrics).  Browsers (Accept:
        text/html) get the HTML metrics page at the same path; scrapers
        negotiate text/plain."""
        if "text/html" in req.headers.get("Accept", ""):
            return await self._make_html_page("metrics", "Metrics")(req)
        from aiohttp import web

        from ray_tpu.util import state

        metrics = await self._offload(state.get_metrics)
        return web.Response(
            text=render_prometheus(metrics),
            content_type="text/plain",
            charset="utf-8",
        )

    async def _profile_stacks(self, req):
        """GET /api/profile/stacks?worker=<hex> — on-demand per-thread
        stacks of a live worker (py-spy role)."""
        from ray_tpu.util import state

        worker = req.query.get("worker", "")
        if not worker:
            return self._json({"error": "pass ?worker=<hex worker id>"})
        try:
            return self._json(
                await self._offload(lambda: state.worker_stacks(worker))
            )
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            return self._json({"error": repr(e)})

    async def _events(self, req):
        from ray_tpu.util import events

        sev = req.query.get("severity")

        def call():
            return events.list_events(severity=sev)

        return self._json(await self._offload(call))

    async def _timeline(self, req):
        return self._json(await self._offload(ray_tpu.timeline))

    def _session_dir(self) -> str:
        return os.environ.get("RT_SESSION_DIR", "/tmp/ray_tpu")

    async def _logs_index(self, req):
        return self._json(await self._page_rows("logs"))

    async def _logs_tail(self, req):
        from aiohttp import web

        name = req.match_info["name"]
        if "/" in name or ".." in name or not name.endswith(".log"):
            return web.Response(status=400, text="bad log name")
        path = os.path.join(self._session_dir(), name)
        if not os.path.exists(path):
            return web.Response(status=404, text="no such log")
        lines = int(req.query.get("lines", "200"))
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 256 * 1024))
            tail = f.read().decode("utf-8", "replace").splitlines()[-lines:]
        return web.Response(text="\n".join(tail), content_type="text/plain")

    # -- HTML pages ------------------------------------------------------
    # Server-rendered first paint (the data is IN the HTML — no JS
    # needed to see live state), then a fetch-refresh keeps it current.
    # Function parity with the reference dashboard's pages
    # (ray: dashboard/client/src/pages/ — cluster/actors/jobs/...), not
    # framework parity: tables over the same JSON the API serves.

    async def _page_rows(self, kind: str):
        """ONE rows provider per subsystem, consumed by both the JSON
        API handlers and the HTML pages — the two surfaces must never
        diverge on what the data is."""
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.util import events as events_mod
        from ray_tpu.util import state

        if kind == "jobs":
            def call():
                rt = get_runtime()
                return rt._run(rt.gcs.call("list_jobs", {}))
        elif kind == "events":
            def call():
                return events_mod.list_events()
        elif kind == "metrics":
            def call():
                return state.get_metrics()
        elif kind == "logs":
            d = self._session_dir()

            def call():
                try:
                    return [
                        {
                            "name": f,
                            "size": os.path.getsize(os.path.join(d, f)),
                        }
                        for f in sorted(os.listdir(d))
                        if f.endswith(".log")
                    ]
                except FileNotFoundError:
                    return []
        else:
            fn = getattr(state, f"list_{kind}")

            def call():
                return fn()
        return await self._offload(call)

    def _make_html_page(self, kind: str, title: str):
        async def handler(req):
            from aiohttp import web

            try:
                rows = await self._page_rows(kind)
            except Exception as e:  # noqa: BLE001 — page must render
                rows = [{"error": repr(e)}]
            raw_html = kind == "logs"
            if raw_html:
                rows = [
                    {**r, "name": f'<a href="/logs/{r["name"]}">'
                                  f'{r["name"]}</a>'}
                    for r in rows
                ]
            page = _render_page(
                title, kind, _render_table(rows, raw=raw_html),
                api=f"/api/{kind}",
                client_refresh=not raw_html,
            )
            return web.Response(text=page, content_type="text/html")

        return handler

    async def _log_page(self, req):
        from aiohttp import web

        import html as _html

        name = req.match_info["name"]
        if "/" in name or ".." in name or not name.endswith(".log"):
            return web.Response(status=400, text="bad log name")
        path = os.path.join(self._session_dir(), name)
        if not os.path.exists(path):
            return web.Response(status=404, text="no such log")
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - 256 * 1024))
            tail = f.read().decode("utf-8", "replace").splitlines()[-500:]
        body = (
            f"<pre id=\"log\">{_html.escape(chr(10).join(tail))}</pre>"
            f"<script>setInterval(async()=>{{"
            f"const r=await fetch('/api/logs/{name}?lines=500');"
            f"document.getElementById('log').textContent=await r.text();"
            f"}},3000)</script>"
        )
        return web.Response(
            text=_render_page(f"log: {name}", "logs", body),
            content_type="text/html",
        )


def start_dashboard(port: int = 8265, host: str = "127.0.0.1") -> str:
    """Start (or reuse) the cluster dashboard; returns its URL.

    ``host`` is the bind address on whichever node hosts the dashboard
    actor.  The localhost default is safe (no auth on the endpoints);
    multi-node operators who need remote access pass ``host="0.0.0.0"``
    explicitly and front it themselves.
    """
    actor = DashboardActor.options(
        name=DASHBOARD_NAME, get_if_exists=True, lifetime="detached",
        num_cpus=0.1,
    ).remote(port, host)
    bound = ray_tpu.get(actor.start.remote(), timeout=120)
    display = "127.0.0.1" if host in ("0.0.0.0", "127.0.0.1") else host
    return f"http://{display}:{bound}"


def stop_dashboard() -> None:
    try:
        actor = ray_tpu.get_actor(DASHBOARD_NAME)
        ray_tpu.kill(actor)
    except Exception:
        pass
