"""`python -m ray_tpu <cmd>` — the CLI entrypoint (scripts.py)."""

from ray_tpu.scripts import main

main()
