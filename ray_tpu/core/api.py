"""Public API: init/shutdown/remote/get/put/wait/kill/cancel.

Role-equivalent of ray: python/ray/_private/worker.py (init:1214, get:2537,
put:2655, wait:2720, remote:3212).
"""

from __future__ import annotations

import atexit
import os
from typing import Any, List, Optional, Sequence, Union

from ray_tpu.common.config import cfg
from ray_tpu.core import node as node_mod
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor  # noqa: F401
from ray_tpu.core.errors import RayTpuError
from ray_tpu.core.object_ref import ObjectRef  # noqa: F401
from ray_tpu.core.runtime import ObjectRefGenerator  # noqa: F401
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.runtime import Runtime, get_runtime, set_runtime

_node_group: Optional[node_mod.NodeProcessGroup] = None


def is_initialized() -> bool:
    from ray_tpu.core import runtime as rt_mod

    return rt_mod._global_runtime is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[dict] = None,
    object_store_bytes: int = 0,
    session_dir: Optional[str] = None,
    labels: Optional[dict] = None,
    log_to_driver: bool = True,
) -> dict:
    """Start (or connect to) a cluster and attach this process as a driver.

    With no address: starts a head node (GCS + raylet) locally, like the
    reference's `ray.init()` standalone mode.  With an address (`host:port`
    of the GCS): connects to the existing cluster and uses a raylet on this
    host.

    ``log_to_driver`` (default True, like the reference): every print /
    stderr write inside tasks and actors of THIS job is streamed back and
    printed here with a ``(pid=..., node=...)`` prefix.
    """
    global _node_group
    if is_initialized():
        raise RayTpuError("ray_tpu.init() called twice; call shutdown() first")

    if address is None:
        sdir = session_dir or node_mod.default_session_dir()
        res = node_mod.detect_resources(num_cpus, num_tpus, resources)
        gcs_proc, gcs_addr = node_mod.start_gcs(sdir)
        try:
            raylet_proc, raylet_addr, node_id, store_path = node_mod.start_raylet(
                gcs_addr, sdir, res, labels=labels,
                store_capacity=object_store_bytes,
            )
        except Exception:
            gcs_proc.terminate()
            raise
        _node_group = node_mod.NodeProcessGroup(
            session_dir=sdir,
            gcs_address=gcs_addr,
            raylet_address=raylet_addr,
            node_id=node_id,
            store_path=store_path,
            gcs_proc=gcs_proc,
            raylet_proc=raylet_proc,
        )
        atexit.register(shutdown)
    else:
        gcs_addr = address
        raylet_addr, node_id, store_path = _find_local_raylet(gcs_addr)

    rt = Runtime(
        gcs_address=gcs_addr,
        node_id=node_id,
        raylet_address=raylet_addr,
        store_path=store_path,
        mode="driver",
    )
    try:
        rt.connect()
    except Exception:
        if _node_group is not None:
            _node_group.kill()
            _node_group = None
        raise
    set_runtime(rt)
    if log_to_driver:
        from ray_tpu.core import log_streaming

        rt.subscribe(
            "worker_logs",
            log_streaming.make_driver_printer(
                rt.job_id.hex() if rt.job_id else None
            ),
        )
    return {
        "gcs_address": gcs_addr,
        "node_id": node_id,
        "session_dir": _node_group.session_dir if _node_group else None,
    }


def _find_local_raylet(gcs_addr: str):
    """Connect to the cluster and locate a raylet on this host."""
    import asyncio

    from ray_tpu.core import rpc

    async def _query():
        conn = await rpc.connect(gcs_addr)
        nodes = await conn.call("get_nodes", {})
        await conn.close()
        return nodes

    nodes = asyncio.run(_query())
    alive = [n for n in nodes if n["alive"]]
    if not alive:
        raise RayTpuError(f"no alive nodes in cluster at {gcs_addr}")
    chosen = alive[0]
    store_path = f"/dev/shm/rt_store_{chosen['node_id'][:12]}"
    if not os.path.exists(store_path):
        raise RayTpuError(
            "no raylet on this host (store arena missing); start one with "
            "cluster_utils or run the driver on a cluster node"
        )
    return chosen["address"], chosen["node_id"], store_path


def shutdown() -> None:
    global _node_group
    from ray_tpu.core import runtime as rt_mod

    if rt_mod._global_runtime is not None:
        rt_mod._global_runtime.shutdown()
    if _node_group is not None:
        _node_group.kill()
        _node_group = None
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass


def remote(*args, **kwargs):
    """Decorator making a function a remote task or a class an actor."""

    def wrap(target):
        import inspect

        if inspect.isclass(target):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return wrap(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return wrap


def method(**kwargs):
    """Decorator for actor methods (e.g. num_returns); stored as metadata."""

    def wrap(m):
        m.__rt_method_opts__ = kwargs
        return m

    return wrap


def get(refs, *, timeout: Optional[float] = None):
    return get_runtime().get(refs, timeout=timeout)


def put(value) -> ObjectRef:
    return get_runtime().put(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    return get_runtime().wait(
        list(refs), num_returns=num_returns, timeout=timeout,
        fetch_local=fetch_local,
    )


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    get_runtime().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref, *, force: bool = False) -> bool:
    """Cancel the task producing ``ref``: queued tasks are dropped before
    dispatch; running tasks are interrupted on their worker (ray:
    worker.py cancel → CoreWorker::CancelTask).  An ObjectRefGenerator
    cancels its producing generator; the consumer's next() then yields a
    ref raising TaskCancelledError."""
    if isinstance(ref, ObjectRefGenerator):
        return get_runtime().stream_cancel(ref.task_id)
    return get_runtime().cancel(ref)


def available_resources() -> dict:
    return get_runtime().cluster_resources()["available"]


def cluster_resources() -> dict:
    return get_runtime().cluster_resources()["total"]


def nodes() -> list:
    return get_runtime().nodes()


class _RuntimeContext:
    @property
    def job_id(self):
        return get_runtime().job_id

    @property
    def node_id(self):
        return get_runtime().node_id

    @property
    def worker_id(self):
        return get_runtime().worker_id

    @property
    def actor_id(self):
        return get_runtime().actor_id

    def get(self):
        return self


def get_runtime_context() -> _RuntimeContext:
    return _RuntimeContext()


def timeline() -> list:
    """Task lifecycle events recorded by this process: submit events plus
    worker-side execution spans piggybacked on task replies (ray:
    ray.timeline chrome-trace export role)."""
    from ray_tpu.core.runtime import get_runtime

    return get_runtime().timeline()
