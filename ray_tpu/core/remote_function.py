"""@remote functions.

Role-equivalent of ray: python/ray/remote_function.py:40 (RemoteFunction,
_remote:266).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu.common.config import cfg
from ray_tpu.common.resources import validate_task_resources


def _build_resources(
    num_cpus=None, num_tpus=None, num_gpus=None, memory=None, resources=None
) -> Dict[str, float]:
    out: Dict[str, float] = dict(resources or {})
    out["CPU"] = num_cpus if num_cpus is not None else out.get("CPU", 1)
    if num_tpus:
        out["TPU"] = num_tpus
    if num_gpus:
        out["GPU"] = num_gpus
    if memory:
        out["memory"] = memory
    if out.get("CPU") == 0:
        out.pop("CPU")
    validate_task_resources(out)
    return out


class RemoteFunction:
    def __init__(self, fn, **default_opts):
        self._fn = fn
        self._opts = default_opts
        # spec template (runtime.TaskTemplate), built at first submit and
        # reused for every later `.remote()` on this option-set: function
        # shipping, resource validation, scheduling-class key and the
        # spec skeleton are paid once, not per call
        self._template = None
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._opts)
        merged.update(opts)
        return RemoteFunction(self._fn, **merged)

    def bind(self, *args, **kwargs):
        """Lazy task-DAG binding (ray: python/ray/dag/function_node.py);
        consumed by ray_tpu.workflow for durable graphs."""
        from ray_tpu.workflow.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        tmpl = self._template
        if tmpl is None or tmpl.rt() is not rt:
            # first submit on this runtime (or the runtime was recycled
            # by shutdown/init): build and cache the template
            tmpl = self._build_template(rt)
        # single ObjectRef, list of refs, or ObjectRefGenerator — the
        # template path already returns the caller-facing shape
        return rt.submit_task_from_template(tmpl, args, kwargs)

    def _build_template(self, rt):
        import inspect

        o = self._opts
        resources = _build_resources(
            o.get("num_cpus"), o.get("num_tpus"), o.get("num_gpus"),
            o.get("memory"), o.get("resources"),
        )
        num_returns = o.get("num_returns", 1)
        # generator functions stream by default (reference: generators
        # return ObjectRefGenerator, remote_function.py:343-349)
        if num_returns == 1 and (
            inspect.isgeneratorfunction(self._fn)
            or inspect.isasyncgenfunction(self._fn)
        ):
            num_returns = "streaming"
        tmpl = rt.make_task_template(
            self._fn,
            name=o.get("name") or self._fn.__qualname__,
            num_returns=num_returns,
            resources=resources,
            max_retries=o.get(
                "max_retries", cfg.task_max_retries_default
            ),
            strategy=_strategy_dict(o.get("scheduling_strategy")),
            runtime_env=o.get("runtime_env"),
        )
        self._template = tmpl
        return tmpl

    def __getstate__(self):
        # the template caches runtime-bound state (the Runtime itself,
        # with its loop futures) — it never ships; the receiver rebuilds
        # its own at first submit
        d = self.__dict__.copy()
        d["_template"] = None
        return d

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__qualname__} cannot be called directly; "
            "use .remote()"
        )


def _strategy_dict(strategy) -> dict:
    if strategy is None:
        return {}
    if isinstance(strategy, dict):
        return strategy
    # scheduling_strategies objects expose to_dict()
    return strategy.to_dict()
