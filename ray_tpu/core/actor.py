"""Actor classes and handles.

Role-equivalent of ray: python/ray/actor.py (ActorClass:563, ActorHandle:1223,
restart options :75-97).
"""

from __future__ import annotations

import weakref
from typing import Any, Dict, Optional

from ray_tpu.common.config import cfg
from ray_tpu.common.ids import ActorID
from ray_tpu.core.remote_function import _build_resources, _strategy_dict
from ray_tpu.core.runtime import get_runtime


class ActorMethod:
    __slots__ = (
        "_handle", "_name", "_num_returns", "_concurrency_group",
        "_skeleton", "_fill_job", "_rt",
    )

    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 concurrency_group: Optional[str] = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group
        # cached spec skeleton (see Runtime.make_actor_skeleton), built at
        # first submit and keyed on the runtime instance — `.remote()`
        # then only fills task id + args
        self._skeleton = None
        self._fill_job = False
        self._rt = None

    def __reduce__(self):
        # the cached skeleton holds runtime-bound state — rebuild bare on
        # the receiving side (first submit there re-warms its own cache)
        return (
            ActorMethod,
            (self._handle, self._name, self._num_returns,
             self._concurrency_group),
        )

    def options(self, num_returns: int = 1,
                concurrency_group: Optional[str] = None) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._name, num_returns, concurrency_group
        )

    def bind(self, *args):
        """Lazy DAG binding (ray: python/ray/dag/class_node.py).  Returns
        a ClassMethodNode for `experimental_compile()`."""
        from ray_tpu.dag.compiled_dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args)

    def remote(self, *args, **kwargs):
        rt = get_runtime()
        if self._rt is None or self._rt() is not rt:
            self._skeleton, self._fill_job = rt.make_actor_skeleton(
                self._handle._actor_id, self._name, self._num_returns,
                self._concurrency_group,
            )
            # weakref: the cached skeleton must not pin a shut-down
            # runtime alive across init/shutdown cycles
            self._rt = weakref.ref(rt)
        # bare ObjectRef / list / ObjectRefGenerator — already the
        # caller-facing shape
        return rt.submit_actor_task_from_skeleton(
            self._skeleton, self._fill_job, args, kwargs,
            self._handle._max_task_retries,
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        # cache on the instance: `handle.method` resolves from __dict__
        # with no allocation on every later lookup, and the cached
        # ActorMethod keeps its spec skeleton warm across calls
        # (pickling is unaffected — __reduce__ carries only the id)
        m = ActorMethod(self, name)
        self.__dict__[name] = m
        return m

    def _apply(self, fn, *args, **kwargs):
        """Run `fn(actor_instance, *args, **kwargs)` inside the actor
        process (reference: ActorHandle.__ray_call__).  Used by compiled
        DAGs to park exec loops on actors; generally useful for
        introspection and weight extraction without touching the user's
        class."""
        return ActorMethod(self, "__rt_apply__").remote(fn, *args, **kwargs)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._max_task_retries))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return (
            isinstance(other, ActorHandle) and other._actor_id == self._actor_id
        )


class ActorClass:
    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._opts = default_opts

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._opts)
        merged.update(opts)
        return ActorClass(self._cls, **merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        o = self._opts
        # actors default to 0 CPU (like the reference) unless asked
        resources = _build_resources(
            o.get("num_cpus", 0), o.get("num_tpus"), o.get("num_gpus"),
            o.get("memory"), o.get("resources"),
        )
        max_task_retries = o.get("max_task_retries", 0)
        actor_id = get_runtime().create_actor(
            self._cls,
            args,
            kwargs,
            name=o.get("name"),
            namespace=o.get("namespace", "default"),
            get_if_exists=o.get("get_if_exists", False),
            resources=resources,
            max_restarts=o.get(
                "max_restarts", cfg.actor_max_restarts_default
            ),
            max_task_retries=max_task_retries,
            detached=(o.get("lifetime") == "detached"),
            strategy=_strategy_dict(o.get("scheduling_strategy")),
            runtime_env=o.get("runtime_env"),
            max_concurrency=o.get("max_concurrency"),
            concurrency_groups=o.get("concurrency_groups"),
            method_groups=self._method_groups(),
            on_drain=o.get("on_drain", "migrate"),
        )
        return ActorHandle(actor_id, max_task_retries)

    def _method_groups(self):
        """Per-method concurrency-group assignments declared with
        @ray_tpu.method(concurrency_group=...)."""
        out = {}
        for name in dir(self._cls):
            if name.startswith("__"):
                continue
            m = getattr(self._cls, name, None)
            opts = getattr(m, "__rt_method_opts__", None)
            if opts and opts.get("concurrency_group"):
                out[name] = opts["concurrency_group"]
        return out

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated directly; "
            "use .remote()"
        )


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    """Look up a named actor (ray: ray.get_actor)."""
    from ray_tpu.core.errors import RayTpuError

    rt = get_runtime()
    info = rt._run(
        rt.gcs.call("get_actor", {"name": name, "namespace": namespace})
    )
    if info is None or info["state"] == "DEAD":
        raise RayTpuError(f"no live actor named {name!r} in namespace {namespace!r}")
    return ActorHandle(ActorID(info["actor_id"]))
