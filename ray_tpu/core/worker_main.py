"""Worker process: executes tasks and hosts actors.

Role-equivalent of the reference's worker-side CoreWorker task execution
(ray: core_worker.cc ExecuteTask:2852, HandlePushTask:3424, the scheduling
queues in core_worker/transport/, and _raylet.pyx execute_task:1721).

Execution model: the runtime's asyncio loop owns all I/O; user code runs on
a single executor thread (sync tasks and sync actor methods — which also
gives per-worker FIFO) or directly on the loop (async actor methods, with a
max_concurrency semaphore).  Actor calls from one caller execute in
submission order via per-caller sequence gating, like the reference's
ActorSchedulingQueue.
"""

from __future__ import annotations

import asyncio
import contextlib
import inspect
import logging
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ray_tpu.common.config import cfg
from ray_tpu.common.ids import ActorID, NodeID, WorkerID
from ray_tpu.core import rpc
from ray_tpu.core.errors import TaskCancelledError, TaskError
from ray_tpu.core.runtime import Runtime, set_runtime
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)


class WorkerServer:
    def __init__(self, runtime: Runtime):
        self.rt = runtime
        self.server = rpc.Server(
            self._handle, host="127.0.0.1", port=0,
            on_close=runtime._notify_peer_closed,
        )
        self._exec = ThreadPoolExecutor(max_workers=1, thread_name_prefix="rt-exec")
        self._exec_thread_id: Optional[int] = None
        self.actor_instance: Any = None
        self.actor_id: Optional[ActorID] = None
        self._actor_is_async = False
        self._actor_sem: Optional[asyncio.Semaphore] = None
        self._actor_thread_pool = None  # set for threaded sync actors
        # drain-migration capture fence: once this actor's state has been
        # captured (handle_checkpoint_actor), no further call may execute
        # here — post-capture effects would be acked and then lost.
        # _ckpt_unseal releases fence-parked calls if a FAILED capture
        # lifts the seal; _actor_exec_inflight counts admitted executions
        # across every path (executor, thread pools, loop-resident async
        # methods) so the capture can wait for quiescence.
        self._ckpt_sealed = False
        self._ckpt_unseal = asyncio.Event()
        self._actor_exec_inflight = 0
        # last object-plane checkpoint blob this process stored; freed if
        # a later capture finds it unconsumed (reply lost → never parked)
        self._ckpt_blob_oid: Optional[bytes] = None
        self._concurrency_groups: Dict[str, dict] = {}  # name -> sem/pool
        self._method_groups: Dict[str, str] = {}  # method -> group name
        self._running_task_threads: Dict[bytes, int] = {}  # task_id -> thread id
        self._running_tasks: Dict[bytes, dict] = {}  # task_id -> descriptor
        self._cancelled: set = set()
        # Per-caller actor-call ordering state (reference analogue:
        # ActorSchedulingQueue, core_worker/transport/actor_scheduling_queue.h):
        # caller_id -> {"next_seq": int admitted so far,
        #               "waiters": {seq: asyncio.Event},
        #               "inflight": {task_id: asyncio.Future(reply)},
        #               "replies": OrderedDict task_id -> reply (retry dedupe)}
        self._callers: Dict[bytes, dict] = {}
        # Adaptive inline execution of sync actor methods (serial actors
        # only).  The executor hop costs two context switches per call —
        # the dominant term for sub-millisecond methods — so a method
        # that has proven consistently fast runs directly on the io loop.
        # method name -> [fast_streak, demoted]
        self._method_stats: Dict[str, list] = {}
        # subsystems whose sync ops BRIDGE through the io loop (runtime
        # collectives) must never have their calling methods promoted
        # onto that loop — promotion would park the loop on itself.
        # Set via disable_inline_execution(); checked by both inline
        # fast paths.
        self._inline_disabled_reason: Optional[str] = None
        self._sync_exec_inflight = 0  # sync methods currently on the pool
        self._exec_counts = [0, 0]    # [inline runs, pool runs] (status RPC)
        # in-flight streaming generator tasks: task_id -> credit state
        self._out_streams: Dict[bytes, dict] = {}
        # compact-push task templates (data plane v2): tpl_id -> spec
        # skeleton.  The driver ships each skeleton once per connection;
        # later pushes carry only (tpl_id, task_id, args, job).  Process-
        # lifetime cache, bounded by the driver's distinct RemoteFunction
        # option-sets (the same bound as the fn cache).
        self._tpl_cache: Dict[bytes, dict] = {}

    _REPLY_CACHE_PER_CALLER = 256
    _INLINE_AFTER = 10        # samples before a method may promote
    _INLINE_EMA_S = 0.005     # stay inline while the exec-time EMA is under
    _INLINE_DEMOTE_S = 0.05   # one run this long bans inline for good

    async def start(self):
        await self.server.start()
        # capture the executor thread id for cancellation; awaited (not
        # fut.result()) so a slow pool spin-up can't stall the io loop
        fut = self._exec.submit(threading.get_ident)
        self._exec_thread_id = await asyncio.wrap_future(fut)

    async def _handle(self, conn: rpc.Connection, method: str, p: Any):
        if method == "push_task":
            return await self.handle_push_task(p, conn)
        if method == "push_actor_task":
            return await self.handle_push_actor_task(p, conn)
        if method == "stream_ack":
            st = self._out_streams.get(p["task_id"])
            if st is not None:
                st["acked"] = max(st["acked"], p["upto"])
                st["credit"].set()
            return True
        if method == "create_actor":
            return await self.handle_create_actor(p)
        if method == "checkpoint_actor":
            return await self.handle_checkpoint_actor(p)
        if method == "checkpoint_abort":
            return await self.handle_checkpoint_abort()
        if method == "bind_env":
            os.environ.update(p["env"])
            _apply_jax_platform(p["env"])
            if p.get("runtime_env"):
                from ray_tpu.core import runtime_env as rtenv_mod

                async def _kv_get(sha):
                    return await self.rt.gcs.call("get_blob", {"sha": sha})

                await rtenv_mod.apply(p["runtime_env"], _kv_get)
            return True
        if method == "cancel_task":
            return self._cancel(p["task_id"])
        if method == "exit_worker":
            logger.info("exit requested: %s", p.get("reason"))
            threading.Thread(target=_exit_soon, daemon=True).start()
            return True
        if method == "ping":
            return {"pid": os.getpid(), "actor": bool(self.actor_instance)}
        if method == "chaos_partition":
            # raylet fan-out of a network-partition install: this worker
            # shares its node's network fate (common/faults.py link cuts)
            from ray_tpu.common import faults

            faults.cut_link(p["src"], p["dst"], p.get("duration_s"))
            return True
        if method == "chaos_heal":
            from ray_tpu.common import faults

            faults.heal_link(p.get("src"), p.get("dst"))
            return True
        if method == "dump_stacks":
            # on-demand stack capture (reference role: the dashboard's
            # py-spy integration, dashboard/modules/reporter/
            # profile_manager.py:83 — here native: every thread's Python
            # stack, no external profiler binary)
            import traceback

            frames = sys._current_frames()
            threads = {t.ident: t.name for t in threading.enumerate()}
            out = {}
            for ident, frame in frames.items():
                name = threads.get(ident, f"thread-{ident}")
                out[f"{name} ({ident})"] = "".join(
                    traceback.format_stack(frame)
                )
            return {"pid": os.getpid(), "stacks": out}
        if method == "status":
            # live task/actor view for the state API (ray: util/state)
            return {
                "pid": os.getpid(),
                "actor_class": type(self.actor_instance).__name__
                if self.actor_instance is not None
                else None,
                "running_tasks": list(self._running_tasks.values()),
                "exec_counts": {
                    "inline": self._exec_counts[0],
                    "pool": self._exec_counts[1],
                },
            }
        sub = self.rt._rpc_subhandlers.get(method)
        if sub is not None:
            return await sub(conn, p)
        raise rpc.RpcError(f"worker: unknown method {method!r}")

    # ---- normal tasks --------------------------------------------------
    def _expand_task_wire(self, t: tuple) -> dict:
        """Rebuild the full spec dict from a compact template push:
        ``(tpl_id, task_id, args, job[, skeleton])`` — the skeleton rides
        along on the first push over a connection and is cached here, so
        the driver never copies the spec per call."""
        if len(t) == 5:
            skel = t[4]
            self._tpl_cache[t[0]] = skel
        else:
            skel = self._tpl_cache.get(t[0])
            if skel is None:
                # driver believed the skeleton was already here (e.g. a
                # restarted worker reached through a recycled connection);
                # an RpcError reply breaks the lease, and the retry lands
                # with a fresh sent-set that re-ships the skeleton
                raise rpc.RpcError(
                    f"unknown task template {t[0].hex()}"
                )
        spec = dict(skel)
        spec["task_id"] = t[1]
        spec["args"] = t[2]
        if t[3]:
            spec["job"] = t[3]
        return spec

    async def handle_push_task(self, spec, conn=None) -> dict:
        if type(spec) is tuple:
            spec = self._expand_task_wire(spec)
        if spec.get("job"):
            # log-streaming attribution + nested submissions inherit it
            self.rt._current_job_hex = spec["job"]
        try:
            fn = await self.rt.resolve_fn(spec["fn_hash"])
        except Exception as e:
            return self._error_reply(e, spec)
        if spec.get("streaming") or inspect.iscoroutinefunction(fn):
            try:
                args, kwargs = await self.rt.unpack_args(spec["args"])
            except Exception as e:
                return self._error_reply(e, spec)
            if spec.get("streaming"):
                return await self._run_streaming(
                    conn, spec, fn, args, kwargs, self._exec
                )
            try:
                with _maybe_execute_span(spec):
                    result = await fn(*args, **kwargs)
                return self._exec_pack(spec, result)
            except Exception as e:
                return self._error_reply(e, spec)
        # sync function: proven-fast fns run inline on the io loop (the
        # executor is ONE thread, so execution is serial either way and
        # inline only skips its two context switches — the same
        # promote/demote contract as actor methods)
        key = "task:" + spec["fn_hash"].hex()
        reply = self._maybe_execute_task_inline(fn, key, spec)
        if reply is not None:
            return reply
        try:
            args, kwargs = await self.rt.unpack_args(spec["args"])
        except Exception as e:
            return self._error_reply(e, spec)
        self._sync_exec_inflight += 1
        try:
            # the streak is noted inside _execute_sync with PURE execution
            # time (queue wait excluded): pure time is what an inline run
            # would cost the loop, and for a serial executor the pool can
            # never overlap — so pipelined windows must still be able to
            # promote (r4 regression: queue-wait-inclusive timing kept
            # every windowed call on the pool forever)
            reply = await asyncio.get_running_loop().run_in_executor(
                self._exec, self._execute_sync, fn, args, kwargs, spec
            )
        finally:
            self._sync_exec_inflight -= 1
        return reply

    def disable_inline_execution(self, reason: str) -> None:
        """Permanently route this worker's sync methods through the
        executor pool.  Called by subsystems whose blocking ops await
        io-loop traffic (util.collective): a loop-inlined caller would
        deadlock the loop it bridges into."""
        self._inline_disabled_reason = reason

    def _maybe_execute_task_inline(self, fn, key: str, spec):
        """Plain-task twin of _maybe_execute_inline: run a proven-fast
        sync function directly on the io loop.  Same safety conditions —
        nothing on the executor (serial semantics preserved), ref-free
        args, sub-2ms streak; same tail-risk bound (one slow run demotes
        permanently past 50 ms)."""
        if self._sync_exec_inflight or self._inline_disabled_reason:
            return None
        st = self._method_stats.get(key)
        if (
            st is None or st[1] or st[0] < self._INLINE_AFTER
            or st[2] >= self._INLINE_EMA_S
        ):
            return None
        try:
            unpacked = self.rt.unpack_args_sync(spec["args"])
        except Exception as e:
            # a bad ARG (undeserializable payload) is the caller's error,
            # not a worker crash — letting it escape would surface as
            # RESPONSE_ERR and tear the healthy lease down
            return self._error_reply(e, spec)
        if unpacked is None:
            return None
        tid = spec["task_id"]
        if tid in self._cancelled:
            self._cancelled.discard(tid)
            return self._error_reply(TaskCancelledError("cancelled"), spec)
        t0_wall = time.time()
        # time ONLY fn(): all four note sites (inline + pool, task +
        # actor) must measure the same quantity or the EMA flaps between
        # promote and demote for methods with expensive serialization;
        # noted in a finally so slow RAISING runs demote/ban too
        t0 = time.perf_counter()
        try:
            args, kwargs = unpacked
            try:
                with _maybe_execute_span(spec):
                    result = fn(*args, **kwargs)
            finally:
                self._note_method_time(key, time.perf_counter() - t0)
            reply = self._exec_pack(spec, result)
            # exec span for the timeline, both reply shapes (promoted
            # fns must not vanish from dashboards)
            if type(reply) is tuple:
                reply = (reply[0], reply[1], t0_wall, time.time())
            else:
                reply["exec_span"] = (t0_wall, time.time())
        except TaskCancelledError as e:
            reply = self._error_reply(e, spec)
        except BaseException as e:
            reply = self._error_reply(
                e if isinstance(e, Exception) else RuntimeError(repr(e)),
                spec,
            )
        finally:
            self._cancelled.discard(tid)
        return reply

    def _execute_sync(self, fn, args, kwargs, spec) -> dict:
        tid = spec["task_id"]
        if tid in self._cancelled:  # cancelled while queued on the executor
            self._cancelled.discard(tid)
            return self._error_reply(TaskCancelledError("cancelled"), spec)
        self._running_task_threads[tid] = threading.get_ident()
        self._running_tasks[tid] = {
            "task_id": tid.hex(),
            "name": spec.get("name") or "<task>",
            "start_time": time.time(),
        }
        try:
            t0 = time.time()
            t0p = time.perf_counter()
            try:
                with _maybe_execute_span(spec):
                    result = fn(*args, **kwargs)
            finally:
                # finally: slow raising runs must demote/ban too
                self._note_method_time(
                    "task:" + spec["fn_hash"].hex(),
                    time.perf_counter() - t0p,
                )
            reply = self._exec_pack(spec, result)
            if type(reply) is tuple:  # compact ("i", payload) fast shape
                return (reply[0], reply[1], t0, time.time())
            reply["exec_span"] = (t0, time.time())
            return reply
        except TaskCancelledError as e:
            return self._error_reply(e, spec)
        except BaseException as e:
            if tid in self._cancelled:
                return self._error_reply(TaskCancelledError(str(e)), spec)
            return self._error_reply(e, spec)
        finally:
            self._running_task_threads.pop(tid, None)
            self._running_tasks.pop(tid, None)
            self._cancelled.discard(tid)

    # ---- streaming generator tasks --------------------------------------
    # Reference: streaming generators (_raylet.pyx:273 ObjectRefGenerator,
    # core_worker task output streaming).  Items ship as stream_item
    # notifies over the duplex connection that carried the push; the RPC
    # reply closes the stream with the total item count.  `stream_ack`
    # notifies from the consumer advance the credit window.

    async def _run_streaming(
        self, conn, spec, fn, args, kwargs, pool, sem=None
    ) -> dict:
        tid = spec["task_id"]
        state = {"acked": -1, "sent": 0, "credit": asyncio.Event()}
        self._out_streams[tid] = state
        loop = asyncio.get_running_loop()
        err: Optional[BaseException] = None
        try:
            if tid in self._cancelled:
                self._cancelled.discard(tid)
                raise TaskCancelledError("cancelled before start")
            if inspect.isasyncgenfunction(fn):
                # Generator methods count against the actor/group
                # concurrency limit for their whole lifetime, like the
                # non-streaming async path (sync generators are bounded
                # by the pool they occupy below).
                async with sem if sem is not None else contextlib.nullcontext():
                    async for item in fn(*args, **kwargs):
                        await self._stream_send(conn, spec, state, item)
            else:
                def pump():
                    # sync generator on the executor thread; each item ships
                    # through the loop synchronously, so backpressure stalls
                    # the generator itself
                    self._running_task_threads[tid] = threading.get_ident()
                    self._running_tasks[tid] = {
                        "task_id": tid.hex(),
                        "name": spec.get("name") or spec.get("method")
                        or "<generator>",
                        "start_time": time.time(),
                    }
                    try:
                        for item in fn(*args, **kwargs):
                            if tid in self._cancelled:
                                raise TaskCancelledError("cancelled")
                            asyncio.run_coroutine_threadsafe(
                                self._stream_send(conn, spec, state, item),
                                loop,
                            ).result()
                    finally:
                        self._running_task_threads.pop(tid, None)
                        self._running_tasks.pop(tid, None)

                await loop.run_in_executor(pool, pump)
        except BaseException as e:
            err = e if isinstance(e, Exception) else RuntimeError(repr(e))
        if err is not None:
            # deliver the error as the stream's final item (the consumer's
            # next() hands back a ref that raises), then close normally.
            # Must run BEFORE the state pop: error sends skip backpressure,
            # but the state must stay reachable for stream_ack handlers.
            try:
                await self._stream_send(conn, spec, state, None, error=err)
            except Exception:
                pass  # conn gone: the caller already failed the stream
        self._out_streams.pop(tid, None)
        self._cancelled.discard(tid)
        return {"status": "ok", "streaming": state["sent"]}

    async def _stream_send(self, conn, spec, state, item, error=None):
        idx = state["sent"]
        if error is None:
            # error items skip backpressure: a consumer that stopped
            # acking (cancel/abandon) must not deadlock the closing send
            if spec["task_id"] in self._cancelled:
                raise TaskCancelledError("cancelled")
            while idx - state["acked"] > cfg.streaming_backpressure_items:
                state["credit"].clear()
                await state["credit"].wait()
                if spec["task_id"] in self._cancelled:
                    raise TaskCancelledError("cancelled")
        from ray_tpu.common.ids import task_return_binary

        if error is not None:
            terr = error if isinstance(error, TaskError) else (
                TaskError.from_exception(
                    error,
                    task_desc=spec.get("name") or spec.get("method", "task"),
                )
            )
            payload = ("err", self.rt.serialize(terr).to_bytes())
        else:
            s, nested = self.rt._serialize_tracked(item)
            if s.total_bytes <= cfg.inline_object_max_bytes:
                payload = ("inline", s.to_bytes())
            else:
                oid = task_return_binary(spec["task_id"], idx)
                # windowed announce (BENCH.md multi-client term (c)): the
                # GCS directory parks location lookups behind a waiter, so
                # a cross-node consumer racing the flush window resolves
                # the moment the batched announce lands
                self.rt._write_to_store(oid, s, urgent_announce=False)
                self.rt._register_edges(oid, nested)
                payload = ("stored", s.total_bytes)
        await conn.notify("stream_item", {
            "task_id": spec["task_id"],
            "index": idx,
            "item": payload,
        })
        state["sent"] = idx + 1

    def _exec_pack(self, spec, result):
        n = spec["num_returns"]
        if n == 1:
            # hot path: single return, inline-sized → compact tuple reply
            # ("i", payload); the caller's _apply_task_reply fast-branch
            # consumes it (dict replies remain for every other shape)
            s, nested = self.rt._serialize_tracked(result)
            if s.total_bytes <= cfg.inline_object_max_bytes:
                return ("i", s.to_bytes())
            from ray_tpu.common.ids import task_return_binary

            oid = task_return_binary(spec["task_id"], 0)
            # windowed announce (BENCH.md multi-client term (c)): a same-
            # node caller resolves the "stored" reply straight off the
            # shared arena (no directory read), and a cross-node pull
            # parks on the GCS location waiter until the batched announce
            # lands ≤ one flush window later — per-result notify rpcs
            # were one of the three multi-client put costs itemized in
            # the roofline
            self.rt._write_to_store(oid, s, urgent_announce=False)
            self.rt._register_edges(oid, nested)
            return {"status": "ok", "returns": [("stored", s.total_bytes)]}
        values = list(result)
        if len(values) != n:
            raise ValueError(
                f"task declared num_returns={n} but returned {len(values)}"
            )
        from ray_tpu.common.ids import task_return_binary

        tid = spec["task_id"]
        returns = []
        for i, v in enumerate(values):
            s, nested = self.rt._serialize_tracked(v)
            if s.total_bytes <= cfg.inline_object_max_bytes:
                # inline: the caller deserializes immediately, so nested
                # refs become live ObjectRefs there — no edge needed
                returns.append(("inline", s.to_bytes()))
            else:
                oid = task_return_binary(tid, i)
                self.rt._write_to_store(oid, s, urgent_announce=False)
                self.rt._register_edges(oid, nested)
                returns.append(("stored", s.total_bytes))
        return {"status": "ok", "returns": returns}

    def _error_reply(self, e, spec) -> dict:
        if isinstance(e, TaskError):
            err = e
        else:
            err = TaskError.from_exception(
                e, task_desc=spec.get("name") or spec.get("method", "task")
            )
        return {"status": "error", "error": self.rt.serialize(err).to_bytes()}

    def _cancel(self, task_id: bytes) -> bool:
        thread_id = self._running_task_threads.get(task_id)
        self._cancelled.add(task_id)
        st = self._out_streams.get(task_id)
        if st is not None:
            # wake a producer parked in the backpressure credit wait — the
            # async-exc below cannot land while its pump thread is blocked
            # inside run_coroutine_threadsafe(...).result()
            st["credit"].set()
        if thread_id is not None:
            import ctypes

            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(thread_id), ctypes.py_object(TaskCancelledError)
            )
            return True
        return False

    # ---- actors --------------------------------------------------------
    async def handle_create_actor(self, p) -> bool:
        spec = p["creation_spec"]
        if p.get("accelerator_env"):
            os.environ.update(p["accelerator_env"])
            _apply_jax_platform(p["accelerator_env"])
        cls = await self.rt.resolve_fn(spec["cls_hash"])
        args, kwargs = await self.rt.unpack_args(spec["args"])
        self.actor_id = ActorID(p["actor_id"])
        self.rt.actor_id = self.actor_id
        # async actor iff any public method is a coroutine function
        self._actor_is_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(cls, predicate=inspect.isfunction)
        )
        self._actor_sem = asyncio.Semaphore(spec.get("max_concurrency") or 1000)
        # threaded sync actors (reference: threaded actors via
        # max_concurrency on a non-async class): methods run on a pool of
        # N threads instead of the single ordered executor thread.
        # Admission stays per-caller-ordered (seq), but executions
        # overlap — the same relaxation the reference documents.
        mc = spec.get("max_concurrency") or 1
        if not self._actor_is_async and mc > 1:
            import concurrent.futures

            self._actor_thread_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=mc, thread_name_prefix="actor-mc"
            )
        # Named concurrency groups (reference: python/ray/actor.py:521-539):
        # each group gets its own limit — a semaphore for async methods, a
        # thread pool for sync ones — so saturating one group never blocks
        # another.  Method→group defaults come from @method(
        # concurrency_group=...); per-call .options() overrides.
        self._concurrency_groups = {}
        self._method_groups = dict(spec.get("method_groups") or {})
        for gname, limit in (spec.get("concurrency_groups") or {}).items():
            import concurrent.futures

            self._concurrency_groups[gname] = {
                "sem": asyncio.Semaphore(limit),
                "pool": concurrent.futures.ThreadPoolExecutor(
                    max_workers=limit,
                    thread_name_prefix=f"actor-cg-{gname}",
                ),
            }
        if spec.get("job"):
            self.rt._current_job_hex = spec["job"]
        from ray_tpu.core import log_streaming

        if log_streaming._publisher is not None:
            # driver-side log prefix becomes "(ClassName pid=..., ...)"
            log_streaming._publisher.set_actor_name(cls.__name__)
        loop = asyncio.get_running_loop()
        self.actor_instance = await loop.run_in_executor(
            self._exec, lambda: cls(*args, **kwargs)
        )
        # graceful-drain handoff: restore the migrated state (opt-in
        # __rt_checkpoint__/__rt_restore__ pair), then re-join any
        # collective groups the predecessor process was a member of —
        # the replacement-reform path, with survivors nudged via pubsub
        blob = p.get("checkpoint")
        blob_ref = p.get("checkpoint_ref")
        restore = getattr(self.actor_instance, "__rt_restore__", None)
        if callable(restore) and (blob is not None or blob_ref is not None):
            state, have = None, False
            if blob is not None:
                state = self.rt.deserialize(blob)
                have = True
                src = f"{len(blob)} bytes inline"
            else:
                # object-plane blob: pull over the data plane (the
                # draining source node is still alive — the drain holds
                # the kill until migration completes).  A lost blob
                # (drain fell back to hard death before a copy escaped)
                # degrades to a fresh start, like a failed capture.
                deadline = (
                    time.monotonic() + cfg.actor_ckpt_fetch_timeout_s
                )
                try:
                    (state,) = await self.rt._get_async(
                        [blob_ref], deadline
                    )
                    have = True
                    src = f"object {blob_ref.hex()[:12]}"
                except Exception:
                    logger.exception(
                        "actor %s checkpoint blob %s unavailable; "
                        "restoring fresh", self.actor_id,
                        blob_ref.hex()[:12],
                    )
            if have:
                await loop.run_in_executor(self._exec, restore, state)
                logger.info(
                    "actor %s state restored from drain checkpoint "
                    "(%s)", self.actor_id, src,
                )
        for g in p.get("collective_groups") or ():
            try:
                await self._rejoin_collective_group(g)
            except Exception:
                logger.exception(
                    "collective group %r re-join failed after migration; "
                    "the group stays un-reformed (destroy + re-init "
                    "recovers)", g.get("group_name"),
                )
        logger.info("actor %s created (%s)", self.actor_id, cls.__name__)
        return True

    async def _rejoin_collective_group(self, g: dict):
        """Re-join one group after a drain migration: publish the reform
        event so the surviving ranks enter the same-world replacement
        reform, then join under the predecessor's rank."""
        from ray_tpu.util.collective import collective as col_mod

        mgr = col_mod._manager()
        self.rt.publish(
            col_mod.reform_channel(g["group_name"]),
            {
                "world_size": g["world_size"],
                "origin_rank": g["rank"],
            },
        )
        await mgr.reform_group(
            g["group_name"], g["world_size"], rank=g["rank"],
            backend_name=g.get("backend"),
        )
        logger.info(
            "re-joined collective group %r as rank %d after migration",
            g["group_name"], g["rank"],
        )

    async def handle_checkpoint_actor(self, p) -> dict:
        """Drain-time state capture (GCS → worker): runs the opt-in
        ``__rt_checkpoint__`` hook and reports this process's collective
        group memberships.  A half-implemented hook pair (rtlint RT113)
        degrades to unsupported — the actor restarts fresh.

        Blobs at most ``actor_ckpt_inline_max_bytes`` ride inline over
        this conn into GCS KV, bit-for-bit the original path.  Larger
        blobs (a pipeline stage's params + optimizer state) are stored
        in the shm object plane — written via the vectored single-pass
        put and announced urgently so the restoring worker's pull finds
        the location — and only the 16-byte object id crosses the
        control plane; the GCS frees the object after the restore."""
        groups = []
        if "ray_tpu.util.collective.collective" in sys.modules:
            from ray_tpu.util.collective import collective as col_mod

            groups = col_mod.local_group_memberships()
        inst = self.actor_instance
        ck = getattr(inst, "__rt_checkpoint__", None) if inst else None
        restore = getattr(inst, "__rt_restore__", None) if inst else None
        if not callable(ck) or not callable(restore):
            return {"supported": False, "blob": None, "groups": groups}
        loop = asyncio.get_running_loop()
        # Capture fence: seal admission BEFORE the hook runs, then wait
        # for every already-admitted execution to finish.  Admitted calls
        # complete and their effects land in the capture (their replies
        # stay valid); calls arriving after the seal park unreplied and
        # die with this worker, becoming retries against the RESTORED
        # actor.  Without the fence, a call slipping in between capture
        # and the kill executes+acks here but its effects are absent from
        # the migrated state — an acked-but-lost mutation.  The
        # quiescence wait (not FIFO ordering) is what makes this hold for
        # async actors, threaded sync actors, and concurrency-group
        # methods too, whose executions do not serialize through
        # self._exec.  Unbounded on purpose — the outer drain deadline is
        # the bound, and every successful-capture path ends in this
        # worker's death, so sealing cannot strand callers.
        self._ckpt_sealed = True
        # ONE persistent event, cleared on seal — never replaced: calls
        # parked during an earlier capture must wake on ANY later unseal
        # (a swapped-in fresh event would strand them forever)
        self._ckpt_unseal.clear()
        try:
            # bounded: a re-entrant call chain (m1 awaiting self.m2 —
            # the inner call is parked on the fence m1 is counted
            # against) can never quiesce; proceeding with a possibly
            # torn capture after the budget beats burning the whole
            # drain deadline into the hard-death fallback
            quiesce_end = (
                time.monotonic() + cfg.actor_ckpt_quiesce_timeout_s
            )
            while self._actor_exec_inflight:
                if time.monotonic() >= quiesce_end:
                    logger.warning(
                        "actor %s capture proceeding with %d calls "
                        "still in flight after %.0fs quiescence wait "
                        "(re-entrant call pattern?); their effects may "
                        "miss the migrated state", self.actor_id,
                        self._actor_exec_inflight,
                        cfg.actor_ckpt_quiesce_timeout_s,
                    )
                    break
                await asyncio.sleep(0.02)
            state = await loop.run_in_executor(self._exec, ck)
            # the capture now owns re-delivery: stop this doomed
            # process's p2p channel streaming (in-flight sends are
            # cancelled, reform listeners deregistered) — the restored
            # twin's checkpointed outbox re-offers on reform, and
            # without the teardown the old incarnation keeps pushing
            # chunks it already captured, burning the drain window on
            # dead traffic.  Ordered AFTER the capture (the outbox
            # snapshot must precede the cancel) and BEFORE serialize.
            if "ray_tpu.util.collective.channel" in sys.modules:
                from ray_tpu.util.collective import channel as channel_mod

                channel_mod.drain_teardown()
            s = self.rt.serialize(state)
            # a previous capture's object-plane blob was never consumed
            # (its reply was lost, or that drain fell over before the
            # restore): this process is still alive, so that migration
            # never happened — free the orphan instead of leaking a
            # protected primary in the node arena, whatever size THIS
            # capture turns out to be (double-free of a consumed blob is
            # a benign tombstone hit).  Swap-then-free, NOT
            # check-free-clear: the free awaits GCS, and a concurrent
            # capture (rpc retry after a lost reply) or abort runs on
            # this same loop during that await — clearing AFTER it acts
            # on a stale pre-await read and stomps whatever they set,
            # orphaning a tracked blob (rtlint RT302)
            orphan, self._ckpt_blob_oid = self._ckpt_blob_oid, None
            await self._free_ckpt_blob(orphan)
            if s.total_bytes > cfg.actor_ckpt_inline_max_bytes:
                from ray_tpu.common.ids import ObjectID

                oid = ObjectID.random().binary()
                # executor, not the loop: the arena write may need the
                # spill-and-retry path, which must not block the io loop
                await loop.run_in_executor(
                    self._exec,
                    lambda: self.rt._write_to_store(oid, s,
                                                    urgent_announce=True),
                )
                # same swap discipline as above: a concurrent capture may
                # have tracked ITS blob during the store await; free it
                # as we take over tracking, or it leaks untracked
                stale, self._ckpt_blob_oid = self._ckpt_blob_oid, oid
                await self._free_ckpt_blob(stale)
                logger.info(
                    "actor %s checkpoint blob (%d bytes) stored in the "
                    "object plane as %s", self.actor_id, s.total_bytes,
                    oid.hex()[:12],
                )
                return {"supported": True, "blob": None, "blob_ref": oid,
                        "blob_bytes": s.total_bytes, "groups": groups}
            return {"supported": True, "blob": s.to_bytes(),
                    "groups": groups}
        except BaseException:
            # a failed capture degrades to a fresh migration (or, with no
            # restart budget, to serving until the kill) — lift the fence
            # AND release the calls parked on it, so "keeps serving" does
            # not become "hangs until node death"
            self._ckpt_sealed = False
            self._ckpt_unseal.set()
            raise

    async def _free_ckpt_blob(self, oid: Optional[bytes]) -> None:
        """Best-effort free of an orphaned checkpoint blob.  Callers
        must have already swapped the oid out of ``_ckpt_blob_oid``
        BEFORE awaiting this (so a concurrent capture/abort never sees
        — and double-handles — an oid that is being freed)."""
        if oid is None:
            return
        try:
            await self.rt.gcs.call(
                "free_objects", {"object_ids": [oid]}, timeout=10.0
            )
        except Exception:
            # unreachable GCS: the node's death still bounds the orphan
            pass

    async def handle_checkpoint_abort(self) -> bool:
        """GCS → worker: the migration this capture was for is NOT
        happening (checkpoint rpc failed GCS-side and the actor is being
        left to serve) — lift the capture fence, release parked calls,
        and free the now-orphaned object-plane blob (nothing will ever
        consume it, and as a protected primary it would pin arena space
        for the node's remaining life).  Idempotent; a no-op on a
        never-sealed worker."""
        if self._ckpt_sealed:
            logger.info(
                "actor %s capture fence aborted by GCS; resuming service",
                self.actor_id,
            )
        self._ckpt_sealed = False
        self._ckpt_unseal.set()
        oid, self._ckpt_blob_oid = self._ckpt_blob_oid, None
        if oid is not None:
            try:
                await self.rt.gcs.call(
                    "free_objects", {"object_ids": [oid]}, timeout=10.0
                )
            except Exception:
                # unreachable GCS: the next capture's self-cleanup (or
                # the node's death) still bounds the orphan
                self._ckpt_blob_oid = oid
        return True

    async def handle_push_actor_task(self, spec, conn=None) -> dict:
        """Per-caller submission ordering, enforced by sequence number.

        Calls are ADMITTED in `seq` order (buffered while earlier seqs are
        in flight over a reconnecting transport).  Default sync actors
        then enter a single executor thread in admission order — which
        gives per-caller execution order even when a retry races fresh
        calls on a new TCP connection.  Threaded sync actors
        (max_concurrency > 1) keep only admission order: executions run
        on a thread pool and may overlap/complete out of order, the same
        relaxation the reference documents for threaded actors.  Retries of a task that already ran (or is running) are
        deduplicated by task_id and answered from the reply cache instead of
        re-executing — exactly-once against an alive actor (reference:
        ActorSchedulingQueue sequence numbers + duplicate suppression).
        Async methods run concurrently under the semaphore (admission order
        only), like the reference's out-of-order queue for async actors."""
        caller = spec.get("caller_id", b"")
        seq = spec.get("seq")
        epoch = spec.get("seq_epoch", 0)
        tid = spec["task_id"]
        if spec.get("job"):
            self.rt._current_job_hex = spec["job"]
        cs = self._callers.get(caller)
        if cs is None:
            cs = self._callers[caller] = {
                "epochs": {},     # epoch -> {"next_seq", "waiters", "dead"}
                "max_epoch": -1,
                "inflight": {},   # task_id -> Future(reply)
                "replies": {},    # task_id -> reply (cross-epoch dedupe)
            }
        if seq is not None:
            if epoch > cs["max_epoch"]:
                cs["max_epoch"] = epoch
                # the caller reconnected: abandon ordering state of older
                # epochs (their unadmitted calls are re-pushed under the
                # new epoch; parked coroutines must not wait forever)
                for old in list(cs["epochs"]):
                    if old < epoch:
                        es = cs["epochs"].pop(old)
                        es["dead"] = True
                        for ev in es["waiters"].values():
                            ev.set()
            elif epoch < cs["max_epoch"]:
                return self._error_reply(
                    RuntimeError(
                        f"stale actor call from abandoned connection epoch "
                        f"{epoch} (current {cs['max_epoch']})"
                    ),
                    spec,
                )
            es = cs["epochs"].get(epoch)
            if es is None:
                es = cs["epochs"][epoch] = {
                    "next_seq": 0, "waiters": {}, "dead": False,
                }
            if seq < es["next_seq"]:
                # duplicate delivery of an already-admitted seq: answer
                # from the reply cache (or share the running execution) —
                # never re-execute
                if tid in cs["replies"]:
                    return cs["replies"][tid]
                fut = cs["inflight"].get(tid)
                if fut is not None:
                    return await asyncio.shield(fut)
                # no record: the reply aged out of the cache — it already
                # executed; report rather than rerun
                return self._error_reply(
                    RuntimeError(
                        f"duplicate actor call (seq {seq} already executed, "
                        f"reply no longer cached)"
                    ),
                    spec,
                )
            while seq > es["next_seq"] and not es["dead"]:
                # park keyed by OUR seq; the predecessor wakes exactly us
                ev = es["waiters"].setdefault(seq, asyncio.Event())
                await ev.wait()
                ev.clear()
            if es["dead"]:
                return self._error_reply(
                    RuntimeError("connection epoch abandoned mid-wait"), spec
                )
            # admit: bump next_seq BEFORE executing so the successor can
            # queue into the executor right behind us (FIFO thread = order)
            es["next_seq"] = seq + 1
            es["waiters"].pop(seq, None)
            nxt = es["waiters"].get(es["next_seq"])
            if nxt is not None:
                nxt.set()

        # Retry dedupe AFTER seq admission: a re-pushed call must still
        # consume its slot in the new epoch (or its successors would park
        # forever), but must not re-execute — completed → cached reply;
        # still running → share its outcome.
        if tid in cs["replies"]:
            return cs["replies"][tid]
        fut = cs["inflight"].get(tid)
        if fut is not None:
            return await asyncio.shield(fut)

        while self._ckpt_sealed:
            # drain-migration capture fence (see handle_checkpoint_actor):
            # this actor's state is being captured for migration — park
            # so the call dies UNREPLIED with this worker and is retried
            # against the restored actor.  Cached replies above still
            # serve (their effects are in the capture).  A failed or
            # aborted capture sets the (persistent) unseal event,
            # releasing the parked calls to execute normally.
            await self._ckpt_unseal.wait()

        # Method / instance / concurrency-group resolution ALL happen
        # after seq admission and before the inflight future exists: an
        # error return earlier would leave the failed call's seq slot
        # unconsumed (every later call from this caller parks on
        # `seq > next_seq` forever — and h.typo.remote() is reachable by
        # any user, ActorHandle does no client-side method validation);
        # an error return after registering reply_fut would leave a
        # never-resolved future that a retried push awaits forever.
        if self.actor_instance is None:
            return self._cache_reply(cs, tid, self._error_reply(
                RuntimeError("actor instance not created on this worker"),
                spec,
            ))
        if spec["method"] == "__rt_apply__":
            # generic in-actor apply (reference: __ray_call__): first arg
            # is a function called as fn(instance, *rest) — the compiled
            # DAG exec loop rides this, as can any diagnostic.
            inst = self.actor_instance

            def method(__fn, *a, **kw):
                return __fn(inst, *a, **kw)
        else:
            try:
                method = getattr(self.actor_instance, spec["method"])
            except AttributeError as e:
                return self._cache_reply(cs, tid, self._error_reply(e, spec))

        # concurrency group: explicit per-call choice, else the method's
        # declared group, else the default (flat) limits.  An unknown
        # name is an ERROR — silently falling back would strip the limit
        # the caller asked for (the reference raises too).
        gname = spec.get("concurrency_group") or self._method_groups.get(
            spec["method"]
        )
        cg = self._concurrency_groups.get(gname) if gname else None
        if gname and cg is None:
            return self._cache_reply(cs, tid, self._error_reply(
                ValueError(
                    f"unknown concurrency group {gname!r}; declared "
                    f"groups: {sorted(self._concurrency_groups)}"
                ),
                spec,
            ))

        reply_fut: asyncio.Future = asyncio.get_running_loop().create_future()
        cs["inflight"][tid] = reply_fut
        # counted for the capture fence's quiescence wait; no await sits
        # between the fence check above and this increment, so a sealing
        # checkpoint either sees the call here or it parks on the fence
        self._actor_exec_inflight += 1
        try:
            if spec.get("streaming"):
                try:
                    args, kwargs = await self.rt.unpack_args(spec["args"])
                except Exception as e:
                    reply = self._error_reply(e, spec)
                else:
                    reply = await self._run_streaming(
                        conn, spec, method, args, kwargs,
                        (cg["pool"] if cg else None)
                        or self._actor_thread_pool or self._exec,
                        sem=(cg["sem"] if cg else self._actor_sem),
                    )
            elif inspect.iscoroutinefunction(method):
                try:
                    args, kwargs = await self.rt.unpack_args(spec["args"])
                except Exception as e:
                    reply = self._error_reply(e, spec)
                else:
                    async with (cg["sem"] if cg else self._actor_sem):
                        self._running_tasks[tid] = {
                            "task_id": tid.hex(),
                            "name": spec.get("name")
                            or spec.get("method")
                            or "<async method>",
                            "start_time": time.time(),
                        }
                        try:
                            with _maybe_execute_span(spec):
                                result = await method(*args, **kwargs)
                            reply = self._exec_pack(spec, result)
                        except Exception as e:
                            reply = self._error_reply(e, spec)
                        finally:
                            self._running_tasks.pop(tid, None)
            else:
                reply = None if cg else self._maybe_execute_inline(
                    method, spec
                )
                if reply is not None:
                    self._exec_counts[0] += 1
                else:
                    pool = (
                        cg["pool"] if cg
                        else self._actor_thread_pool or self._exec
                    )
                    self._exec_counts[1] += 1
                    self._sync_exec_inflight += 1
                    try:
                        # streak noted inside _execute_sync_method with
                        # PURE execution time — see handle_push_task
                        reply = await asyncio.get_running_loop().run_in_executor(
                            pool, self._execute_sync_method, method, spec
                        )
                    finally:
                        self._sync_exec_inflight -= 1
        except BaseException as e:
            reply = self._error_reply(
                e if isinstance(e, Exception) else RuntimeError(repr(e)), spec
            )
        finally:
            self._actor_exec_inflight -= 1
        cs["inflight"].pop(tid, None)
        self._cache_reply(cs, tid, reply)
        if not reply_fut.done():
            reply_fut.set_result(reply)
        return reply

    def _cache_reply(self, cs, tid, reply) -> dict:
        """Insert into the per-caller reply cache with the size bound
        applied (every insertion path must trim, or a caller repeatedly
        hitting an error path grows the cache without bound)."""
        cs["replies"][tid] = reply
        while len(cs["replies"]) > self._REPLY_CACHE_PER_CALLER:
            cs["replies"].pop(next(iter(cs["replies"])))
        return reply

    def _maybe_execute_inline(self, method, spec) -> Optional[dict]:
        """Run a proven-fast sync method directly on the io loop, skipping
        the executor's two context switches.  Inline is taken only when it
        cannot be observed: the actor is serial (no thread pool), nothing
        is running on the executor (so executions can't overlap), the args
        are ref-free (resolving a ref needs the loop), and the method's
        recent-execution-time EMA is under _INLINE_EMA_S.  First calls
        always go through the pool, so a blocking method never runs
        inline.  The tail risk — a promoted method whose NEXT run turns
        slow blocks the loop for that one run, and cancellation cannot
        interrupt it — is bounded by demotion: any run past
        _INLINE_DEMOTE_S (50 ms) bans the method from inline permanently,
        and a sustained slowdown drags the EMA over the bar.
        Returns None when the pool must be used."""
        if (
            self._actor_thread_pool is not None
            or self._sync_exec_inflight
            or self._inline_disabled_reason
        ):
            return None
        mname = spec["method"]
        if mname == "__rt_apply__":
            # generic apply carries a DIFFERENT callable per call under
            # one stats key: past sub-2ms calls predict nothing about
            # the next one (e.g. collective init bridging into this
            # very loop) — promotion is unsound here by construction
            return None
        st = self._method_stats.get(mname)
        if (
            st is None or st[1] or st[0] < self._INLINE_AFTER
            or st[2] >= self._INLINE_EMA_S
        ):
            return None
        unpacked = self.rt.unpack_args_sync(spec["args"])
        if unpacked is None:
            return None
        tid = spec["task_id"]
        if tid in self._cancelled:
            self._cancelled.discard(tid)
            return self._error_reply(TaskCancelledError("cancelled"), spec)
        try:
            args, kwargs = unpacked
            # time ONLY the method call (matches the pool path's
            # estimator — timing pack here too made the EMA disagree
            # between paths and flap promote/demote); noted in a finally
            # so slow raising runs demote/ban as well
            t0 = time.perf_counter()
            try:
                result = method(*args, **kwargs)
            finally:
                self._note_method_time(mname, time.perf_counter() - t0)
            reply = self._exec_pack(spec, result)
        except TaskCancelledError as e:
            reply = self._error_reply(e, spec)
        except BaseException as e:
            reply = self._error_reply(
                e if isinstance(e, Exception) else RuntimeError(repr(e)), spec
            )
        finally:
            self._cancelled.discard(tid)
        return reply

    def _note_method_time(self, mname: str, dt: float):
        # [samples, banned, ema].  An EMA (not a consecutive-fast streak)
        # so one OS-preemption spike — routine on a loaded host, and the
        # r4 regression: a single >2ms measurement de-promoted the method
        # and locked pipelined windows onto the pool — cannot flip a
        # genuinely fast method back to the executor.  A single run past
        # the demote bound still bans inline outright.
        st = self._method_stats.get(mname)
        if st is None:
            st = self._method_stats[mname] = [1, False, dt]
        else:
            st[0] += 1
            st[2] += 0.125 * (dt - st[2])
        if dt > self._INLINE_DEMOTE_S:
            st[1] = True

    def _execute_sync_method(self, method, spec) -> dict:
        tid = spec["task_id"]
        if tid in self._cancelled:
            self._cancelled.discard(tid)
            return self._error_reply(TaskCancelledError("cancelled"), spec)
        self._running_task_threads[tid] = threading.get_ident()
        self._running_tasks[tid] = {
            "task_id": tid.hex(),
            "name": spec.get("name") or spec.get("method") or "<actor method>",
            "start_time": time.time(),
        }
        try:
            unpacked = self.rt.unpack_args_sync(spec["args"])
            if unpacked is None:  # ObjectRef args: resolve on the io loop
                unpacked = self.rt._run(self.rt.unpack_args(spec["args"]))
            args, kwargs = unpacked
            t0p = time.perf_counter()
            try:
                with _maybe_execute_span(spec):
                    result = method(*args, **kwargs)
            finally:
                # finally: slow raising runs must demote/ban too
                self._note_method_time(
                    spec["method"], time.perf_counter() - t0p
                )
            return self._exec_pack(spec, result)
        except TaskCancelledError as e:
            return self._error_reply(e, spec)
        except BaseException as e:
            if tid in self._cancelled:
                return self._error_reply(TaskCancelledError(str(e)), spec)
            return self._error_reply(e, spec)
        finally:
            self._running_task_threads.pop(tid, None)
            self._running_tasks.pop(tid, None)
            self._cancelled.discard(tid)


def _maybe_execute_span(spec):
    """Execute-side span parented under the submitter's context (the
    TaskSpec's trace_ctx carrier); a no-op context when tracing is off
    or the caller sent no context."""
    if tracing.enabled() and spec.get("trace_ctx"):
        return tracing.span(
            f"execute {spec.get('method') or spec.get('name') or 'task'}",
            carrier=spec["trace_ctx"],
            task_id=spec["task_id"].hex(),
        )
    return contextlib.nullcontext()


def _exit_soon():
    time.sleep(0.1)
    from ray_tpu.util.profiling import dump_profile

    dump_profile()
    os._exit(0)


def _apply_jax_platform(env: dict) -> None:
    """Force jax onto the platform the lease assigned.

    JAX_PLATFORMS as an env var is NOT sufficient here: site hooks (e.g.
    the axon TPU tunnel) can register and force their platform at
    interpreter start regardless of env, so a CPU-leased worker would
    still dial the TPU — wedging the single-tenant tunnel for every
    other process.  jax.config wins over the hook as long as no backend
    has initialized, which holds until the first array op in this
    worker.
    """
    jp = env.get("JAX_PLATFORMS")
    if not jp:
        return
    try:
        import jax

        jax.config.update("jax_platforms", jp)
    except Exception as e:  # backend already initialized: too late to move
        logger.warning("could not set jax platform to %r: %s", jp, e)


def main():
    logging.basicConfig(
        level=logging.INFO, format="[worker %(process)d] %(levelname)s %(message)s"
    )
    _apply_jax_platform(os.environ)
    worker_id = WorkerID.from_hex(os.environ["RT_WORKER_ID"])
    raylet_addr = os.environ["RT_RAYLET_ADDR"]
    gcs_addr = os.environ["RT_GCS_ADDR"]
    node_id = os.environ["RT_NODE_ID"]
    store_path = os.environ["RT_STORE_PATH"]

    # partition plane: a worker shares its node's logical endpoint — a
    # node partition cuts the workers' links too (common/faults.py)
    from ray_tpu.common import faults as _faults

    _faults.set_local_endpoint(node_id)

    rt = Runtime(
        gcs_address=gcs_addr,
        node_id=node_id,
        raylet_address=raylet_addr,
        store_path=store_path,
        mode="worker",
        worker_id=worker_id,
    )
    set_runtime(rt)
    from ray_tpu.core import log_streaming

    log_streaming.install_worker_tee(rt)
    server = WorkerServer(rt)
    rt._worker_server = server

    async def boot():
        await server.start()
        raylet_conn = await rpc.connect(
            raylet_addr, server._handle, name="worker->raylet"
        )
        await raylet_conn.call(
            "worker_ready",
            {"worker_id": worker_id.binary(), "address": server.server.address},
        )
        return raylet_conn

    rt.connect()
    if os.environ.get("RT_PROFILE_DIR"):
        # profiled runs: SIGTERM (raylet teardown) must still dump
        import signal
        from ray_tpu.util.profiling import dump_profile as _dump

        def _term(_sig, _frm):
            _dump()
            os._exit(0)

        signal.signal(signal.SIGTERM, _term)
    raylet_conn = asyncio.run_coroutine_threadsafe(boot(), rt._loop).result(30)

    # Block the main thread forever; exit when the raylet connection drops
    # (our parent died) — a worker must never outlive its raylet.
    try:
        while not raylet_conn.closed and not rt.raylet.closed:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    from ray_tpu.util.profiling import dump_profile

    dump_profile()
    os._exit(0)


if __name__ == "__main__":
    main()
