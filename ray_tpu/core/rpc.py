"""Asyncio message transport: symmetric request/response/notify over TCP.

Role-equivalent of the reference's RPC layer (ray: src/ray/rpc/grpc_server.h,
client_call.h) redesigned for a Python-asyncio control plane: one duplex
connection per peer pair carries requests in both directions (so GCS can push
pubsub messages down the same pipe a client calls up on), frames are
length-prefixed pickles, and large binary payloads ride pickle5 out-of-band
buffers to avoid copies.

Wire frame:  [u32 nbufs][u32 len_0]...[u32 len_{n-1}][buf_0]...[buf_{n-1}]
where buf_0 is the message pickle and buf_1.. are out-of-band buffers.
Message: (kind, msg_id, method, payload)  kind: 0=req, 1=resp-ok, 2=resp-err,
3=notify, 4=batch (payload is a list of non-batch messages; one frame, one
pickle parse, applied in arrival order).

Per-tick frame coalescing: `call_soon` requests and request responses do
not write their own frame — they append to a per-connection accumulator
that a `loop.call_soon` callback flushes, so every message issued within
one event-loop tick rides ONE vectored write (and the peer admits the
whole batch from one parse).  Latency-neutral at depth 1: the flush
callback runs before the loop can go back to sleep, and a single pending
message is written as a plain frame — bytes identical to the unbatched
protocol.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import struct
import time
from typing import Any, Awaitable, Callable, Dict, Optional

from ray_tpu.common import faults
from ray_tpu.common.backoff import Backoff, BackoffPolicy
from ray_tpu.common.config import cfg

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")

REQUEST = 0
RESPONSE_OK = 1
RESPONSE_ERR = 2
NOTIFY = 3
BATCH = 4  # payload: list of (kind, msg_id, method, payload) messages

# process-wide outbound REQUEST tally (every Connection.call /
# call_soon, any peer).  Pure diagnostics: the pipeline bench reads
# the delta across a timed step to report driver rpcs per micro-op
# for the handoff A/B — never reset, wrap-free in practice.
CALLS = 0


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class RemoteCallError(RpcError):
    """The peer's handler raised; carries the remote exception."""

    def __init__(self, exc):
        super().__init__(f"remote handler raised: {exc!r}")
        self.remote_exception = exc


def _approx_payload_bytes(obj, depth: int = 3) -> int:
    """Cheap size estimate for batch-accumulator accounting: sums
    bytes-like payload bodies through shallow container nesting (spec
    dict → args list → ("val", b) tuple is depth 3).  Small control
    values estimate 0 — the count cap governs those."""
    t = type(obj)
    if t is bytes or t is bytearray or t is memoryview:
        return len(obj)
    if depth <= 0:
        return 0
    # explicit loops, not sum(genexpr): this runs per queued message on
    # the hot path and a generator object is a tracked gen0 alloc
    n = 0
    if t is tuple or t is list:
        for o in obj:
            n += _approx_payload_bytes(o, depth - 1)
    elif t is dict:
        for v in obj.values():
            n += _approx_payload_bytes(v, depth - 1)
    return n


def _dump(msg) -> list:
    bufs: list = [None]
    meta = pickle.dumps(
        msg, protocol=5, buffer_callback=lambda pb: bufs.append(pb.raw())
    )
    bufs[0] = meta
    return bufs


def _load(bufs: list):
    return pickle.loads(bufs[0], buffers=bufs[1:])


class Connection:
    """One duplex peer connection. Both sides can call() and notify()."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Callable[["Connection", str, Any], Awaitable[Any]],
        name: str = "",
        on_close: Optional[Callable[["Connection"], None]] = None,
        peer_endpoint: Optional[str] = None,
    ):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        self.on_close = on_close
        # logical endpoint of the peer ("gcs", a node id hex) when known
        # — the key the faults.py link-cut (network partition) site
        # matches on; None = unlabeled, never cut
        self.peer_endpoint = peer_endpoint
        self._msg_ids = itertools.count()
        self._pending: Dict[int, asyncio.Future] = {}
        self._send_lock = asyncio.Lock()
        self._closed = False
        self._recv_task: Optional[asyncio.Task] = None
        # per-tick frame coalescing: messages queued by call_soon /
        # response sends, flushed as one BATCH frame at tick end
        self._out_batch: list = []
        self._out_batch_bytes = 0  # _approx_payload_bytes running sum
        self._flush_scheduled = False
        # peers can stash identity here after a hello exchange
        self.peer_info: dict = {}

    def start(self) -> None:
        self._recv_task = asyncio.get_running_loop().create_task(self._recv_loop())

    # -- sending ---------------------------------------------------------
    async def _send(self, msg, urgent: bool = False) -> None:
        bufs = _dump(msg)
        async with self._send_lock:
            if self._closed:
                raise ConnectionLost(f"connection {self.name} is closed")
            # preserve program order with the coalesced path: anything
            # queued this tick goes on the wire before this message.
            # urgent (order-independent liveness traffic — heartbeats)
            # skips both the flush and the drain: its tiny frame must
            # not queue behind a large coalesced batch or a slow peer's
            # flow control — a loaded tick would otherwise delay the
            # detector's input past heartbeat_interval_s and manufacture
            # the exact false positive the health plane exists to avoid
            if self._out_batch and not urgent:
                self._flush_out_batch()
            self._write_frames(bufs)
            if not urgent:
                await self.writer.drain()

    def _write_frames(self, bufs):
        """Synchronous frame write (header + buffers, no await between
        writes — frames never interleave).  ONE encoder for _send and
        call_soon; wire-format changes live here only.

        Small frames coalesce into a single transport write: each write()
        tries a sock.send() when the buffer is empty, so header+payload
        as separate writes costs 2-3 syscalls per message — the dominant
        per-RPC term for control-plane traffic.  Large buffers still pass
        through uncopied (a memcpy of a big payload beats nothing)."""
        # chaos site rpc.link (outbound): a cut (local -> peer) link
        # swallows the frame — partition semantics are silence, not an
        # error, so the sender's call simply never completes
        if faults.LINKS_ACTIVE and self.peer_endpoint is not None:
            if faults.link_is_cut(faults.LOCAL_ENDPOINT, self.peer_endpoint):
                return
        fault_ctl = faults.ACTIVE  # bind once: clear() races the check
        if fault_ctl is not None:
            # chaos site rpc.send.frame: drop (frame vanishes — the peer
            # simply never sees these messages) or reset (transport
            # aborted; both sides observe ConnectionLost and run their
            # real loss paths)
            plan = fault_ctl.hit(faults.SITE_RPC_SEND_FRAME, self.name)
            if plan is not None:
                if plan.action == "drop":
                    return
                if plan.action == "reset":
                    try:
                        self.writer.transport.abort()
                    except Exception:
                        pass
                    return
        header = bytearray(_U32.pack(len(bufs)))
        total = 0
        for b in bufs:
            n = len(b) if isinstance(b, bytes) else b.nbytes
            header += _U32.pack(n)
            total += n
        if total < 65536:
            for b in bufs:
                header += b
            self.writer.write(bytes(header))
            return
        self.writer.write(bytes(header))
        for b in bufs:
            self.writer.write(b)

    async def call(self, method: str, payload: Any = None,
                   timeout: float = None, urgent: bool = False):
        """timeout=None → config default; timeout<0 → wait forever.
        ``urgent`` writes the request as its own lone frame ahead of any
        coalesced batch queued this tick (liveness traffic only)."""
        global CALLS
        if timeout is None:
            timeout = cfg.rpc_call_timeout_s
        elif timeout < 0:
            timeout = None
        CALLS += 1
        msg_id = next(self._msg_ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            await self._send((REQUEST, msg_id, method, payload), urgent)
            return await asyncio.wait_for(fut, timeout=timeout)
        finally:
            self._pending.pop(msg_id, None)

    def call_soon(self, method: str, payload: Any = None) -> "asyncio.Future":
        """Fire a request WITHOUT awaiting transport drain or the reply;
        returns the reply future (completed by the recv loop, failed with
        ConnectionLost on shutdown).  The hot-path primitive for high-rate
        callers (actor pushes): no per-call coroutine/Task, no wait_for
        timer — attach a done-callback instead.  Loop-only.

        Requests issued within one event-loop tick coalesce into a single
        BATCH frame (flushed by a loop.call_soon callback, so a lone
        request still hits the wire before the loop can sleep — depth-1
        latency is unchanged).  NB: skipping drain() skips asyncio's
        write flow control — transport.write buffers unboundedly — so
        callers MUST police `send_backlog` and fall back to an awaiting
        path (conn.drain) past their budget."""
        global CALLS
        if self._closed:
            raise ConnectionLost(f"connection {self.name} is closed")
        CALLS += 1
        msg_id = next(self._msg_ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        self._send_soon((REQUEST, msg_id, method, payload))
        return fut

    def _send_soon(self, msg) -> None:
        """Queue one message for the per-tick batch flush (loop-only)."""
        if self._closed:
            raise ConnectionLost(f"connection {self.name} is closed")
        self._out_batch.append(msg)
        self._out_batch_bytes += _approx_payload_bytes(msg[3])
        if (
            len(self._out_batch) >= cfg.rpc_batch_max_msgs
            or self._out_batch_bytes >= cfg.rpc_batch_max_bytes
        ):
            # count cap: a burst bigger than one tick's worth of batching
            # flushes mid-tick, so transport backlog becomes visible to
            # the callers policing send_backlog before the tick ends.
            # byte cap: large payloads (object chunks, big inline args)
            # must never coalesce into a frame past rpc_max_frame_bytes —
            # a single huge message flushes alone, as its own plain frame
            self._flush_out_batch()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush_out_batch)

    def _flush_out_batch(self) -> None:
        """Write everything queued this tick as one frame.  A single
        queued message is written as a plain (non-BATCH) frame — the
        depth-1 wire bytes are identical to the unbatched protocol."""
        self._flush_scheduled = False
        batch = self._out_batch
        self._out_batch = []
        self._out_batch_bytes = 0
        if not batch or self._closed:
            # closed: _shutdown already failed every pending future;
            # dropping queued messages mirrors a loss mid-flight
            return
        try:
            if len(batch) == 1:
                self._write_frames(_dump(batch[0]))
            else:
                self._write_frames(_dump((BATCH, 0, "", batch)))
        except Exception:
            # transport died under us; the recv loop notices the loss and
            # fails every pending future via _shutdown
            logger.debug("batch flush failed on %s", self.name, exc_info=True)

    @property
    def send_backlog(self) -> int:
        """Bytes sitting unsent in the transport's write buffer."""
        try:
            return self.writer.transport.get_write_buffer_size()
        except Exception:
            return 0

    async def drain(self):
        """Await transport flow control (pauses while the peer is slow).
        Flushes the per-tick batch first so the backlog being drained
        includes everything queued this tick."""
        if self._out_batch:
            self._flush_out_batch()
        await self.writer.drain()

    async def notify(self, method: str, payload: Any = None,
                     urgent: bool = False) -> None:
        await self._send((NOTIFY, 0, method, payload), urgent)

    # -- receiving -------------------------------------------------------
    async def _read_frame(self):
        hdr = await self.reader.readexactly(_U32.size)
        (nbufs,) = _U32.unpack(hdr)
        if nbufs == 0 or nbufs > 1024:
            raise RpcError(f"bad frame: nbufs={nbufs}")
        lens_raw = await self.reader.readexactly(_U32.size * nbufs)
        lens = [_U32.unpack_from(lens_raw, i * _U32.size)[0] for i in range(nbufs)]
        total = sum(lens)
        if total > cfg.rpc_max_frame_bytes:
            raise RpcError(f"frame too large: {total}")
        bufs = []
        for ln in lens:
            bufs.append(await self.reader.readexactly(ln))
        return bufs

    async def _recv_loop(self):
        try:
            while True:
                bufs = await self._read_frame()
                kind, msg_id, method, payload = _load(bufs)
                if kind == BATCH:
                    # one parse for the whole tick's worth of peer
                    # messages; sub-messages apply in arrival order, so
                    # e.g. a run of push_task requests admits (and, with
                    # eager tasks, seq-admits) back-to-back in one pass
                    for kind, msg_id, method, sub in payload:
                        self._dispatch_msg(kind, msg_id, method, sub)
                else:
                    self._dispatch_msg(kind, msg_id, method, payload)
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            BrokenPipeError,
            OSError,
        ):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("rpc recv loop error on %s", self.name)
        finally:
            await self._shutdown()

    def _dispatch_msg(self, kind, msg_id, method, payload):
        """Route one inbound message (loop-only, called by the recv
        loop) — chaos site ``rpc.recv.msg`` guards the real dispatch,
        so drop/delay/dup/error faults apply per MESSAGE (batched and
        plain frames alike)."""
        # chaos site rpc.link (inbound): frames from a cut (peer ->
        # local) link were "lost in the network" — drop before dispatch
        if faults.LINKS_ACTIVE and self.peer_endpoint is not None:
            if faults.link_is_cut(self.peer_endpoint, faults.LOCAL_ENDPOINT):
                return
        fault_ctl = faults.ACTIVE  # bind once: clear() races the check
        if fault_ctl is not None:
            plan = fault_ctl.hit(
                faults.SITE_RPC_RECV_MSG, f"{self.name}:{method}"
            )
            if plan is not None and self._inject_recv_fault(
                plan, kind, msg_id, method, payload
            ):
                return
        self._dispatch_msg_now(kind, msg_id, method, payload)

    def _inject_recv_fault(self, plan, kind, msg_id, method, payload) -> bool:
        """Apply one recv-side fault; True = normal dispatch replaced."""
        act = plan.action
        if act == "drop":
            return True
        if act == "dup":
            # deliver one extra copy; the wrapper delivers the original
            self._dispatch_msg_now(kind, msg_id, method, payload)
            return False
        if act == "delay":
            asyncio.get_running_loop().call_later(
                plan.delay_s, self._dispatch_msg_now,
                kind, msg_id, method, payload,
            )
            return True
        if act == "error":
            injected = RpcError(f"injected fault at rpc.recv.msg:{method}")
            if kind == REQUEST:
                # the handler never runs; the caller sees a remote error
                try:
                    self._send_soon((RESPONSE_ERR, msg_id, method, injected))
                except ConnectionLost:
                    pass
            elif kind == RESPONSE_OK or kind == RESPONSE_ERR:
                # the reply arrives as a failure
                self._dispatch_msg_now(RESPONSE_ERR, msg_id, method, injected)
            # NOTIFY: no reply channel — an errored notify is a drop
            return True
        if act == "reset":
            try:
                self.writer.transport.abort()
            except Exception:
                pass
            return True
        return False

    def _dispatch_msg_now(self, kind, msg_id, method, payload):
        if kind == REQUEST:
            asyncio.get_running_loop().create_task(
                self._handle_request(msg_id, method, payload)
            )
        elif kind == NOTIFY:
            asyncio.get_running_loop().create_task(
                self._handle_notify(method, payload)
            )
        elif kind == BATCH:
            logger.warning("nested BATCH frame on %s dropped", self.name)
        else:
            # pop: call() also pops in its finally (harmless
            # no-op then); call_soon() futures are only removed
            # here or at shutdown
            fut = self._pending.pop(msg_id, None)
            if fut is not None and not fut.done():
                if kind == RESPONSE_OK:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RemoteCallError(payload))

    async def _handle_request(self, msg_id, method, payload):
        try:
            result = await self.handler(self, method, payload)
        except ConnectionLost:
            return
        except Exception as e:
            logger.debug("handler %s raised: %r", method, e)
            result = _safe_exc(e)
            try:
                self._send_soon((RESPONSE_ERR, msg_id, method, result))
            except ConnectionLost:
                pass
            return
        # buffered reply: replies completed within one tick coalesce into
        # a single frame (a handler that ran synchronously under the eager
        # task factory replies in the same tick its request arrived).
        # call_soon's skipped flow control is restored here: past the
        # backlog budget the handler awaits the transport drain.
        try:
            self._send_soon((RESPONSE_OK, msg_id, method, result))
        except ConnectionLost:
            return
        if self.send_backlog > cfg.rpc_send_backlog_limit_bytes:
            try:
                await self.drain()
            except (ConnectionLost, OSError):
                pass

    async def _handle_notify(self, method, payload):
        try:
            await self.handler(self, method, payload)
        except Exception:
            logger.exception("notify handler %s raised", method)

    async def _shutdown(self):
        if self._closed:
            return
        self._closed = True
        self._out_batch.clear()
        self._out_batch_bytes = 0
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            try:
                self.on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def close(self):
        if self._recv_task:
            self._recv_task.cancel()
        await self._shutdown()

    @property
    def closed(self) -> bool:
        return self._closed


def _safe_exc(e: Exception):
    """Make an exception picklable; fall back to a generic RpcError."""
    try:
        pickle.dumps(e)
        return e
    except Exception:
        return RpcError(f"{type(e).__name__}: {e}")


class Server:
    """Accepts connections; each gets the shared handler."""

    def __init__(
        self,
        handler: Callable[[Connection, str, Any], Awaitable[Any]],
        host: str = "127.0.0.1",
        port: int = 0,
        on_connect: Optional[Callable[[Connection], None]] = None,
        on_close: Optional[Callable[[Connection], None]] = None,
    ):
        self.handler = handler
        self.host = host
        self.port = port
        self.on_connect = on_connect
        self.on_close = on_close
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._accept, host=self.host, port=self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def _accept(self, reader, writer):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _socket

            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        conn = Connection(
            reader, writer, self.handler,
            name=f"server@{self.port}", on_close=self._conn_closed,
        )
        self.connections.add(conn)
        if self.on_connect:
            self.on_connect(conn)
        conn.start()

    def _conn_closed(self, conn):
        self.connections.discard(conn)
        if self.on_close:
            self.on_close(conn)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def close(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()


class ReconnectingConnection:
    """A call/notify channel that survives peer restarts.

    Wraps a `Connection` to `address`; when the underlying connection is
    lost (peer crashed or restarted), calls block while a new connection
    is dialed with backoff, `on_reconnect(conn)` re-registers this client
    with the reborn peer, and the call is retried — up to
    `max_downtime_s` of cumulative downtime, after which ConnectionLost
    propagates.  This is the client half of GCS fault tolerance (ray:
    gcs_rpc_client.h reconnection + gcs_client resubscribe behavior):
    servers persist their tables; clients re-attach and replay identity.

    Retried calls must be idempotent — true for the control-plane verbs
    used over this channel (registrations, kv, lookups, notifies).
    """

    def __init__(
        self,
        address: str,
        handler: Callable[[Connection, str, Any], Awaitable[Any]] = None,
        name: str = "",
        on_reconnect: Optional[
            Callable[[Connection], Awaitable[None]]
        ] = None,
        on_give_up: Optional[Callable[[], None]] = None,
        max_downtime_s: float = None,
        peer_endpoint: Optional[str] = None,
    ):
        self.address = address
        self.handler = handler
        self.name = name
        self.peer_endpoint = peer_endpoint  # applied to every dialed conn
        self.on_reconnect = on_reconnect
        self.on_give_up = on_give_up
        self.max_downtime_s = (
            cfg.gcs_reconnect_max_downtime_s
            if max_downtime_s is None
            else max_downtime_s
        )
        self._conn: Optional[Connection] = None
        self._lock = asyncio.Lock()
        self._closed = False

    async def _ensure(self) -> Connection:
        if self._closed:
            raise ConnectionLost(f"{self.name}: channel closed")
        conn = self._conn
        if conn is not None and not conn.closed:
            return conn
        async with self._lock:
            if self._closed:
                raise ConnectionLost(f"{self.name}: channel closed")
            if self._conn is not None and not self._conn.closed:
                return self._conn
            # shared deadline-aware backoff (common/backoff.py): dials
            # de-correlate across the fleet via jitter, and the last
            # sleep clamps to the remaining downtime budget
            redial_backoff = Backoff(
                BackoffPolicy(
                    base_s=cfg.reconnect_backoff_base_s,
                    mult=cfg.backoff_mult,
                    max_s=cfg.reconnect_backoff_max_s,
                    jitter_frac=cfg.backoff_jitter_frac,
                ),
                deadline=time.monotonic() + self.max_downtime_s,
            )
            first_attempt = self._conn is None
            while True:
                conn = None
                try:
                    conn = await connect(
                        self.address, self.handler, name=self.name,
                        peer_endpoint=self.peer_endpoint,
                    )
                    if self.on_reconnect and not first_attempt:
                        await self.on_reconnect(conn)
                    self._conn = conn
                    return conn
                except BaseException as e:
                    # never leak a half-initialized connection (its recv
                    # loop would keep handling server pushes concurrently
                    # with the eventually-installed one)
                    if conn is not None and self._conn is not conn:
                        try:
                            await conn.close()
                        except Exception:
                            pass
                    if not isinstance(
                        e, (OSError, RpcError, asyncio.TimeoutError)
                    ):
                        raise
                    if not await redial_backoff.wait():
                        if self.on_give_up:
                            self.on_give_up()
                        raise ConnectionLost(
                            f"{self.name}: peer at {self.address} unreachable "
                            f"for {self.max_downtime_s:.0f}s ({e!r})"
                        ) from e

    async def call(self, method: str, payload: Any = None,
                   timeout: float = None, urgent: bool = False):
        while True:
            conn = await self._ensure()
            try:
                return await conn.call(method, payload, timeout=timeout,
                                       urgent=urgent)
            except ConnectionLost:
                if self._closed:
                    raise
                continue  # _ensure() re-dials with its own deadline

    async def notify(self, method: str, payload: Any = None,
                     urgent: bool = False) -> None:
        conn = await self._ensure()
        try:
            await conn.notify(method, payload, urgent=urgent)
        except ConnectionLost:
            if self._closed:
                raise
            conn = await self._ensure()
            await conn.notify(method, payload, urgent=urgent)

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def current(self) -> Optional[Connection]:
        """The live underlying Connection, if any (for identity checks)."""
        return self._conn

    async def close(self):
        self._closed = True
        if self._conn is not None:
            await self._conn.close()


async def connect(
    address: str,
    handler: Callable[[Connection, str, Any], Awaitable[Any]] = None,
    name: str = "",
    on_close: Optional[Callable[[Connection], None]] = None,
    timeout: float = None,
    peer_endpoint: Optional[str] = None,
) -> Connection:
    if timeout is None:
        timeout = cfg.rpc_connect_timeout_s
    host, port_s = address.rsplit(":", 1)

    async def _null_handler(conn, method, payload):
        raise RpcError(f"unexpected inbound {method!r} on client-only connection")

    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, int(port_s)), timeout=timeout
    )
    sock = writer.get_extra_info("socket")
    if sock is not None:
        import socket as _socket

        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    conn = Connection(
        reader, writer, handler or _null_handler, name=name or address,
        on_close=on_close, peer_endpoint=peer_endpoint,
    )
    conn.start()
    return conn
