"""ObjectRef: a future handle to a (possibly remote) object.

Role-equivalent of ray: python/ray/_raylet.pyx ObjectRef.  Serializing a ref
(into task args or any container) goes through a custom reducer registered by
the runtime, which promotes the value to the shared store so any process can
resolve it (ray's borrowing protocol, collapsed to promote-on-escape).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.common.ids import ObjectID


class ObjectRef:
    __slots__ = ("object_id", "_owner_hint", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: Optional[str] = None):
        self.object_id = object_id
        self._owner_hint = owner_hint  # node hint for locality-aware pulls
        try:
            from ray_tpu.core import runtime as _rt

            rt = _rt._global_runtime
            if rt is not None:
                rt.on_ref_created(object_id)
        except Exception:
            pass

    def hex(self) -> str:
        return self.object_id.hex()

    def binary(self) -> bytes:
        return self.object_id.binary()

    def __hash__(self):
        return hash(self.object_id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self):
        return f"ObjectRef({self.object_id.hex()[:16]})"

    def future(self):
        """concurrent.futures.Future resolving to the object's value."""
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().as_future(self)

    def __await__(self):
        """Allow `await ref` inside async actors."""
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().await_ref(self).__await__()

    def __reduce__(self):
        # Plain pickle path (no runtime mediation): carry id + hint.
        return (ObjectRef, (self.object_id, self._owner_hint))

    def __del__(self):
        try:
            from ray_tpu.core import runtime as _rt

            rt = _rt._global_runtime
            if rt is not None:
                rt.on_ref_deleted(self.object_id)
        except Exception:
            pass  # interpreter teardown
