"""ObjectRef: a future handle to a (possibly remote) object.

Role-equivalent of ray: python/ray/_raylet.pyx ObjectRef.  Serializing a ref
(into task args or any container) goes through a custom reducer registered by
the runtime, which promotes the value to the shared store so any process can
resolve it (ray's borrowing protocol, collapsed to promote-on-escape).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.common.ids import ObjectID

# Lazily-bound runtime module (circular import: runtime.py imports this
# module at load).  Bound once on first ref construction — an in-function
# import would pay the import-machinery lookup on EVERY ref create/delete,
# which is measurable on the submission hot path.
_rt_mod = None


def _bind_runtime():
    global _rt_mod
    from ray_tpu.core import runtime as _rt

    _rt_mod = _rt
    return _rt


class ObjectRef:
    __slots__ = ("object_id", "_owner_hint", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: Optional[str] = None):
        self.object_id = object_id
        self._owner_hint = owner_hint  # node hint for locality-aware pulls
        m = _rt_mod
        if m is None:
            m = _bind_runtime()
        rt = m._global_runtime
        if rt is not None:
            rt.on_ref_created(object_id)

    def hex(self) -> str:
        return self.object_id.hex()

    def binary(self) -> bytes:
        return self.object_id.binary()

    def __hash__(self):
        return hash(self.object_id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self):
        return f"ObjectRef({self.object_id.hex()[:16]})"

    def future(self):
        """concurrent.futures.Future resolving to the object's value."""
        m = _rt_mod or _bind_runtime()
        return m.get_runtime().as_future(self)

    def __await__(self):
        """Allow `await ref` inside async actors."""
        m = _rt_mod or _bind_runtime()
        return m.get_runtime().await_ref(self).__await__()

    def __reduce__(self):
        # Plain pickle path (no runtime mediation): carry id + hint.
        return (ObjectRef, (self.object_id, self._owner_hint))

    def __del__(self):
        try:
            m = _rt_mod
            if m is None:
                return  # no runtime ever existed: nothing to release
            rt = m._global_runtime
            if rt is not None:
                rt.on_ref_deleted(self.object_id)
        except Exception:
            pass  # interpreter teardown
