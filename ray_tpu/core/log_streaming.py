"""Worker -> driver log streaming.

Role-equivalent of ray: the log monitor + driver-side ``print_logs``
(python/ray/_private/log_monitor.py:103, worker.py print_logs).  The
reference tails every worker's log FILES from a per-node daemon and
routes lines to drivers over GCS pubsub; here the worker itself tees
``sys.stdout``/``sys.stderr`` (the file redirection set up by the raylet
stays in place underneath) and publishes line batches straight to the
``worker_logs`` pubsub channel — no extra daemon, no fs polling.

Caveat (documented divergence): C-level writes that bypass Python's
``sys.stdout`` (native extensions printing from C) land only in the
worker's log file, not on the driver.  ``ray_tpu logs`` tails the files.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional

_FLUSH_INTERVAL_S = 0.1
_FLUSH_BYTES = 8192
_MAX_BUFFERED_LINES = 10_000  # drop (count) beyond this; never OOM


class _TeeStream:
    """File-like wrapper: passes writes through to the real stream (the
    worker's log file) and buffers complete lines for the publisher."""

    def __init__(self, inner, publisher: "_LogPublisher", stream_name: str):
        self._inner = inner
        self._pub = publisher
        self._name = stream_name
        self._partial = ""

    def write(self, s: str) -> int:
        n = self._inner.write(s)
        try:
            self._partial += s
            if "\n" in self._partial:
                *lines, self._partial = self._partial.split("\n")
                self._pub.add(self._name, lines)
        except Exception:
            pass  # streaming must never break user prints
        return n

    def flush(self) -> None:
        self._inner.flush()

    def writelines(self, lines) -> None:
        for line in lines:
            self.write(line)

    def fileno(self) -> int:
        return self._inner.fileno()

    def isatty(self) -> bool:
        return False

    @property
    def encoding(self):
        return getattr(self._inner, "encoding", "utf-8")

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _LogPublisher:
    """Batches teed lines and ships them over GCS pubsub from a small
    daemon thread (user code may print from any thread; publishing from
    the io loop per line would make print() latency depend on the GCS)."""

    def __init__(self, rt):
        self.rt = rt
        self._lock = threading.Lock()
        self._buf: list = []  # (stream, line)
        self._dropped = 0
        self._actor_name: Optional[str] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._flush_loop, name="rt-log-pub", daemon=True
        )
        self._thread.start()

    def set_actor_name(self, name: str) -> None:
        self._actor_name = name

    def add(self, stream: str, lines) -> None:
        with self._lock:
            room = _MAX_BUFFERED_LINES - len(self._buf)
            if room <= 0:
                self._dropped += len(lines)
                return
            if len(lines) > room:
                self._dropped += len(lines) - room
                lines = lines[:room]
            self._buf.extend((stream, ln) for ln in lines)

    def _flush_loop(self) -> None:
        while not self._closed:
            time.sleep(_FLUSH_INTERVAL_S)
            self.flush_now()

    def flush_now(self) -> None:
        with self._lock:
            if not self._buf and not self._dropped:
                return
            buf, self._buf = self._buf, []
            dropped, self._dropped = self._dropped, 0
        job = getattr(self.rt, "_current_job_hex", None)
        msg = {
            "pid": os.getpid(),
            "node": self.rt.node_id,
            "job": job,
            "actor": self._actor_name,
            "lines": [
                {"stream": s, "line": ln} for s, ln in buf
            ],
            "dropped": dropped,
        }
        try:
            self.rt.publish("worker_logs", msg)
        except Exception:
            pass  # GCS unreachable: lines stay in the log file

    def close(self) -> None:
        self._closed = True
        self.flush_now()


_publisher: Optional[_LogPublisher] = None


def install_worker_tee(rt) -> _LogPublisher:
    """Wrap this worker's stdout/stderr so task/actor prints stream to
    the driver.  The raylet's file redirection stays underneath."""
    global _publisher
    if _publisher is not None:
        return _publisher
    _publisher = _LogPublisher(rt)
    sys.stdout = _TeeStream(sys.stdout, _publisher, "stdout")
    sys.stderr = _TeeStream(sys.stderr, _publisher, "stderr")
    return _publisher


# ---- driver side ----------------------------------------------------------

def make_driver_printer(job_hex: Optional[str]):
    """Callback for Runtime.subscribe('worker_logs', ...): prints each
    line with a ``({actor} pid=..., node=...)`` prefix, like the
    reference's colorized ``(pid=..., ip=...)`` prefixes.  Lines from
    other jobs are dropped; lines with no job attribution are shown."""

    def _print(msg: dict) -> None:
        if msg.get("job") and job_hex and msg["job"] != job_hex:
            return
        pid = msg.get("pid")
        node = (msg.get("node") or "")[:8]
        actor = msg.get("actor")
        who = f"{actor} pid={pid}" if actor else f"pid={pid}"
        prefix = f"({who}, node={node}) "
        out = sys.stdout
        err = sys.stderr
        for item in msg.get("lines", ()):
            stream = err if item.get("stream") == "stderr" else out
            try:
                stream.write(prefix + item["line"] + "\n")
            except Exception:
                return
        if msg.get("dropped"):
            try:
                err.write(
                    f"{prefix}[{msg['dropped']} log lines dropped "
                    "(worker buffered too fast)]\n"
                )
            except Exception:
                return
        try:
            out.flush()
        except Exception:
            pass

    return _print
