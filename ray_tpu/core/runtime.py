"""The in-process runtime: task submission, object resolution, actor calls.

Role-equivalent of the reference's CoreWorker (ray:
src/ray/core_worker/core_worker.h:292 — SubmitTask:2128, Get:1523,
SubmitActorTask:2438) plus the client half of its direct task transport
(direct_task_transport.h:75).  Runs inside every driver and worker process:
an asyncio loop on a background thread owns all connections (GCS, local
raylet, peer workers); the public API is synchronous and bridges in via
run_coroutine_threadsafe.

Scheduling fast path: leases are requested from the GCS per scheduling class
and *reused* across tasks with a short idle grace, so a steady stream of
tasks costs one GCS round-trip per worker, not per task (ray:
direct_task_transport.cc lease reuse + pipelining analogue).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import threading
import weakref
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._native.store import (
    ObjectExistsError,
    ShmStore,
    StoreError,
    StoreFullError,
)
from ray_tpu.common.backoff import Backoff, BackoffPolicy
from ray_tpu.common.config import cfg
from ray_tpu.common.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    TaskID,
    WorkerID,
    task_return_binary,
)
from ray_tpu.common import serialization as ser
from ray_tpu.core import rpc
from ray_tpu.core.errors import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

_global_runtime: Optional["Runtime"] = None
_init_lock = threading.Lock()


def get_runtime() -> "Runtime":
    if _global_runtime is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init() first")
    return _global_runtime


def set_runtime(rt: Optional["Runtime"]):
    global _global_runtime
    _global_runtime = rt


# --------------------------------------------------------------------------
# Lease management (client side of scheduling)
# --------------------------------------------------------------------------


@dataclass
class Lease:
    lease_id: int
    worker_addr: str
    worker_id: bytes
    node_id: str
    conn: rpc.Connection
    inflight: int = 0
    broken: bool = False
    draining: bool = False  # a drain-then-pump task is in flight


@dataclass(slots=True)
class PendingTask:
    spec: Any  # wire spec dict; None for compact template-path tasks
    return_ids: Any  # tuple/list of return oid bytes
    retries_left: int
    sub_idx: int = 0  # per-actor submission order (client-side)
    dep_oids: Any = ()  # oids held while in flight (list, or shared ())
    # scheduling-class routing for NORMAL tasks (None for actor tasks):
    # carried on the task so the coalesced submit queue and lineage need
    # no per-call argument tuples
    class_key: Any = None
    resources: Any = None
    strategy: Any = None
    # reply routing (assigned at dispatch; rt/st/conn live on the task's
    # slots so the done-callback is ONE bound method instead of a
    # closure + cells per call).  st is the ActorClientState for actor
    # pushes and the Lease for normal-task pushes.
    rt: Any = None
    st: Any = None
    conn: Any = None
    # Compact template path (data plane v2): the immutable skeleton lives
    # on the TaskTemplate and ships to each worker once per connection;
    # per-call state is just (task_id, args, job) and the wire carries a
    # tuple — the driver never copies the spec dict per call.  ``spec``
    # stays None unless the call needs the full-dict form (streaming,
    # tracing, actor tasks, untemplated submits).
    tmpl: Any = None
    task_id: bytes = b""
    args: Any = ()
    job: Any = None
    streaming: bool = False
    # Slotted lineage record fields: the PendingTask itself IS the lineage
    # record (reference analogue of task_manager.h lineage entries) — no
    # per-task entry dict, no live-returns set; liveness is a bitmask over
    # return_ids positions and the budget rides two int slots.
    lineage_budget: int = 0
    live_mask: int = 0
    recon_inflight: bool = False

    def name(self) -> str:
        if self.tmpl is not None:
            return self.tmpl.skeleton["name"]
        s = self.spec
        return s.get("name") or s.get("method", "") if s else ""

    def on_push_reply(self, fut):
        self.rt._on_push_reply(self.st, self.conn, self, fut)

    def on_task_reply(self, fut):
        self.rt._on_task_push_reply(self, fut)


class _LineageSlots:
    """Slotted lineage store (data plane v2): a preallocated array of
    slots keyed by task-id low bits, with an overflow dict for slot
    collisions.  Records are the PendingTask objects themselves — already
    allocated for submission and reused here, so recording lineage for a
    task costs zero container allocations (v1 paid a 9-key dict + a
    live-returns set per call, the dominant term in the ~25 allocs/call
    normal-task driver path)."""

    __slots__ = ("_mask", "_slots", "_overflow")

    def __init__(self, n_slots: int = 1024):
        assert n_slots & (n_slots - 1) == 0
        self._mask = n_slots - 1
        self._slots: list = [None] * n_slots
        self._overflow: Dict[bytes, Any] = {}

    def insert(self, rec) -> None:
        tid = rec.task_id
        i = (tid[0] | (tid[1] << 8)) & self._mask
        if self._slots[i] is None:
            self._slots[i] = rec
        else:
            self._overflow[tid] = rec

    def get(self, tid: bytes):
        rec = self._slots[(tid[0] | (tid[1] << 8)) & self._mask]
        if rec is not None and rec.task_id == tid:
            return rec
        return self._overflow.get(tid)

    def remove(self, tid: bytes) -> None:
        i = (tid[0] | (tid[1] << 8)) & self._mask
        rec = self._slots[i]
        if rec is not None and rec.task_id == tid:
            self._slots[i] = None
            return
        self._overflow.pop(tid, None)

    def __len__(self) -> int:  # tests/diagnostics
        return sum(1 for r in self._slots if r is not None) + len(
            self._overflow
        )


@dataclass
class ActorClientState:
    """Client half of ordered actor-call transport (reference analogue:
    CoreWorkerDirectActorTaskSubmitter, direct_actor_task_submitter.h:74).

    Wire sequence numbers are assigned per CONNECTION EPOCH: each
    (re)connect bumps `epoch` and restarts `wire_seq` at 0, and unacked
    calls are re-pushed in original submission order — so the server can
    enforce exact per-caller ordering even across reconnects/restarts."""

    queue: Any = None  # deque[PendingTask] in submission order
    inflight: Dict[int, PendingTask] = field(default_factory=dict)  # sub_idx→task
    epoch: int = -1  # bumped to 0 on first connect
    wire_seq: int = 0
    conn: Any = None
    wake: Any = None  # asyncio.Event
    pump_running: bool = False
    dead: bool = False  # actor creation failed / actor died — pump exits
    draining: bool = False  # pump is parked mid-drain waiting for inflight


class TaskTemplate:
    """Pre-computed, immutable submission state for one RemoteFunction
    option-set (reference analogue: the cached TaskSpec prelude ray
    builds once per function descriptor).  Everything that is identical
    across `.remote()` calls — function hash, validated resources,
    scheduling class key, runtime-env descriptor, the spec skeleton
    dict — is computed once at first submit; each call then only fills
    task/object ids and args.  Treat every field as frozen.  ``rt`` is a
    weakref: the template is cached on long-lived RemoteFunction objects
    and must not keep a shut-down Runtime (loop, stores, futures) alive
    across init/shutdown cycles — callers deref it purely as the
    staleness check."""

    __slots__ = (
        "rt", "skeleton", "class_key", "resources", "strategy",
        "num_returns", "streaming", "max_retries", "fill_job", "tpl_id",
    )

    def __init__(self, rt, skeleton, class_key, resources, strategy,
                 num_returns, streaming, max_retries, fill_job):
        self.rt = weakref.ref(rt)
        self.skeleton = skeleton
        self.class_key = class_key
        self.resources = resources
        self.strategy = strategy
        self.num_returns = num_returns
        self.streaming = streaming
        self.max_retries = max_retries
        self.fill_job = fill_job
        # wire identity for the compact push path: the skeleton ships to
        # each worker connection once under this id; subsequent pushes
        # carry only (tpl_id, task_id, args, job)
        self.tpl_id = os.urandom(8)


_sched_class_tags = iter(range(1, 1 << 62))


class SchedClassState:
    def __init__(self):
        # deque: the pump pops from the front at pipeline depth — a list
        # pop(0) is O(queue) and turns a deep windowed burst quadratic
        self.queue: deque = deque()
        self.leases: List[Lease] = []
        self.requests_inflight = 0
        self.idle_timer: Optional[asyncio.TimerHandle] = None
        # wire id for cancel_lease_requests (parked requests at the GCS
        # are cancelled by (client conn, tag) when local demand drains)
        self.tag = next(_sched_class_tags)
        self.cancel_sent = False


# --------------------------------------------------------------------------
# Runtime
# --------------------------------------------------------------------------


_PENDING_RESULT = object()  # lazy marker: locally-pending result, no async waiter yet


def _ignore_pubsub(msg):
    """Placeholder callback holding the "nodes" channel slot: the
    runtime's internal node-event hook runs in _gcs_handler regardless
    of which user callback (if any) owns the slot."""


def lease_pending_backoff() -> Backoff:
    """Backoff between LEASE_PENDING re-requests.  The request_lease
    call itself parks at the GCS until woken or expired, so this sleep
    exists only to DE-CORRELATE re-requests across classes/callers —
    capped well under the grant cadence (a 2 s tail here would idle
    freed capacity).  Shared by both lease loops and sched_bench."""
    return Backoff(BackoffPolicy(
        base_s=cfg.backoff_base_s, mult=cfg.backoff_mult,
        max_s=0.25, jitter_frac=cfg.backoff_jitter_frac,
    ))


class Runtime:
    def __init__(
        self,
        gcs_address: str,
        node_id: str,
        raylet_address: str,
        store_path: str,
        mode: str = "driver",
        worker_id: Optional[WorkerID] = None,
        job_id: Optional[JobID] = None,
    ):
        self.gcs_address = gcs_address
        self.node_id = node_id
        self.raylet_address = raylet_address
        self.mode = mode
        self.worker_id = worker_id or WorkerID.random()
        self.job_id = job_id
        self.actor_id: Optional[ActorID] = None  # set when this worker hosts one

        self._loop = asyncio.new_event_loop()
        # eager tasks (3.12+): create_task runs the coroutine synchronously
        # up to its first await, removing one loop wakeup from every
        # dispatch hop (submit→push, reply fan-out) — worth ~10% on the
        # actor-call round-trip
        try:
            self._loop.set_task_factory(asyncio.eager_task_factory)
        except AttributeError:
            pass
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="rt-io", daemon=True
        )
        self._thread.start()
        from ray_tpu.util.profiling import maybe_enable_loop_profile

        maybe_enable_loop_profile(self._loop, mode)

        self.store = ShmStore(store_path)
        self._zerocopy_threshold = cfg.zerocopy_get_min_bytes
        self.gcs: Optional[rpc.Connection] = None
        self.raylet: Optional[rpc.Connection] = None

        # local object state
        self.memory_store: Dict[bytes, Any] = {}
        self.result_futures: Dict[bytes, asyncio.Future] = {}
        # caller threads parked in get()'s fast path, keyed by oid; the
        # reply applier signals them directly, skipping the
        # run_coroutine_threadsafe round trip (see _try_sync_get).  The
        # lock serializes caller-thread register/drop (the io-loop signal
        # path pops atomically and never takes it).
        # oid -> Event (single waiter) or list of Events (contended)
        self._sync_waiters: Dict[bytes, Any] = {}
        self._sync_reg_lock = threading.Lock()
        self._sync_get_tls = threading.local()  # reusable wait Event
        self._shared: set = set()  # oids known to be in shm + registered
        self._escaped: set = set()  # refs passed on before their task finished

        # streaming generator tasks by task_id (reference:
        # ObjectRefGenerator, python/ray/_raylet.pyx:273): items arrive as
        # stream_item notifies on the worker connection and are buffered
        # here until the consumer's next()
        self._streams: Dict[bytes, "_StreamBuf"] = {}
        # abandoned stream -> consumed-upto index; the closing reply frees
        # the producer-stored items the consumer never took
        self._abandoned_streams: Dict[bytes, int] = {}

        # scheduling
        self._classes: Dict[tuple, SchedClassState] = {}
        self._worker_conns: Dict[str, rpc.Connection] = {}
        self._put_index = 0

        # caller-thread submission coalescing: tasks submitted between
        # two io-loop ticks ride ONE call_soon_threadsafe wakeup (the
        # per-call Handle + args tuple + context copy was a measurable
        # slice of submission churn).  deque ops are GIL-atomic; the
        # flag protocol (drainer clears BEFORE draining, submitters
        # schedule only on a False read) cannot miss a wakeup.
        self._submit_q: deque = deque()
        self._submit_q_scheduled = False

        # flush-window GCS notifications: object-directory notifies
        # (add_object_location / ref_edge / ref_update / free_objects)
        # buffer here and go out as one object_notify_batch rpc — per
        # tick for urgent events, per gcs_notify_flush_window_s for
        # windowed ones.  Ref export and local get-miss flush eagerly,
        # so cross-process visibility semantics are unchanged.
        self._gcs_nbuf: list = []
        self._gcs_nbuf_lock = threading.Lock()
        self._gcs_nbuf_mode: Optional[str] = None  # None | "timer" | "soon"

        # actors (client side)
        self._actor_conns: Dict[bytes, rpc.Connection] = {}
        self._actor_addrs: Dict[bytes, str] = {}
        self._actor_seq: Dict[bytes, int] = {}
        self._actor_states: Dict[bytes, ActorClientState] = {}

        # in-flight dispatch registry for cancellation: first return oid ->
        # (task_id, conn carrying the running call)
        self._inflight_dispatch: Dict[bytes, tuple] = {}
        self._cancel_requested: set = set()  # oids cancelled pre-enqueue

        # function cache (worker side)
        self._fn_cache: Dict[bytes, Any] = {}
        # id(fn) -> (weakref(fn), hash): submit-path memo (see
        # fn_hash_and_register)
        self._fn_hash_memo: Dict[int, tuple] = {}

        # ---- distributed refcounting (reference analogue:
        # core_worker/reference_count.h:61, collapsed to a GCS-tracked
        # holder set per object; this process reports itself as a holder
        # while any local ObjectRef instance or in-flight task arg needs
        # the object, with events batched per flush window) ----
        self._ref_lock = threading.Lock()
        self._local_refs: Dict[bytes, int] = {}   # live ObjectRef instances
        self._task_holds: Dict[bytes, int] = {}   # held as in-flight task deps
        self._ref_registered: set = set()         # ref_add sent (or pending)
        self._pending_ref_add: set = set()
        self._pending_ref_del: set = set()
        # adds skipped at flush time because the value was a LOCAL-ONLY
        # inline result (nothing cluster-side to keep alive); promotion
        # via ensure_shared re-registers (see _flush_ref_events)
        self._deferred_reg: set = set()
        self._ref_flush_scheduled = False

        # ---- lineage (reference analogue: task_manager.h:208 lineage +
        # object_recovery_manager.h:41): keep resubmittable tasks while
        # any of their return refs live, so a lost object re-executes its
        # producing task.  Slotted store: records are the PendingTask
        # objects themselves (see _LineageSlots) ----
        self._lineage = _LineageSlots()
        self._lineage_by_return: Dict[bytes, Any] = {}  # oid -> record
        # lineage re-executions started by this process — the drain
        # plane's "zero reconstructions" acceptance counter
        self.reconstructions = 0

        # subsystem RPC methods: method name -> async handler(conn, payload).
        # Libraries (util.collective is the first) claim a method name and
        # receive every inbound request/notify for it, whichever channel it
        # arrived on — the worker's server or a caller→worker connection.
        self._rpc_subhandlers: Dict[str, Any] = {}
        # peer-connection lifecycle observers: callback(conn) fired on the
        # io loop when any worker-peer connection (dialed or accepted)
        # closes — the liveness signal group-membership code keys off
        self._peer_close_watchers: List[Any] = []

        # pubsub: channel -> callback (driver log streaming rides this)
        self._subscriptions: Dict[str, Any] = {}
        # job attribution for log streaming: drivers use job_id; workers
        # learn it from executed task specs (nested submissions inherit)
        self._current_job_hex: Optional[str] = None
        self._serialization = ser.SerializationContext()
        self._serialization.register_reducer(ObjectRef, self._reduce_ref)
        self._nested_ref_sink = threading.local()
        self._class_runtime_envs: Dict[Any, dict] = {}
        # timeline: bounded ring of task lifecycle events for
        # api.timeline() (ray: ray.timeline / chrome-trace export role).
        # Stored as compact tuples (phase, name, task_id, ts, pid, extra)
        # — the per-call event dict was measurable churn on the task
        # submission path; timeline() rebuilds the dict view on read.
        self._timeline = deque(maxlen=cfg.timeline_max_events)
        self._pid = os.getpid()
        self._closed = False

    def record_event(self, phase: str, name: str, task_id_hex: str,
                     **extra) -> None:
        self._timeline.append(
            (phase, name, task_id_hex, time.time(), self._pid,
             extra or None)
        )

    def _record_exec(self, name: str, task_id_hex: str, worker: str,
                     start: float, dur: float) -> None:
        """kwargs-free twin of record_event for the per-reply exec span
        (the **extra dict per call was pure hot-path churn)."""
        self._timeline.append(
            ("exec", name, task_id_hex, time.time(), self._pid,
             (worker, start, dur))
        )

    def timeline(self) -> list:
        """Chrome-trace-style task lifecycle events recorded by this
        process (submit/start/end with worker-side execution spans)."""
        out = []
        for phase, name, tid, ts, pid, extra in list(self._timeline):
            ev = dict(phase=phase, name=name, task_id=tid, ts=ts, pid=pid)
            if extra is not None:
                if type(extra) is tuple:  # exec-span compact extras
                    ev["worker"], ev["start"], ev["dur"] = extra
                else:
                    ev.update(extra)
            out.append(ev)
        return out

    def _normalize_runtime_env(self, env: Optional[dict]) -> Optional[dict]:
        """Package + upload a runtime_env once; returns the descriptor."""
        if not env:
            return None
        from ray_tpu.core import runtime_env as rtenv_mod

        def kv_put(sha, value):
            if threading.current_thread() is self._thread:
                raise RuntimeError(
                    "runtime_env with working_dir/py_modules cannot be "
                    "packaged from inside an async actor method; submit "
                    "from a sync context"
                )
            self._run(
                self.gcs.call("put_blob", {"sha": sha, "data": value})
            )

        return rtenv_mod.normalize(env, kv_put, scope=self.gcs_address)

    # ---- loop bridging -------------------------------------------------
    def _run(self, coro, timeout: Optional[float] = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        try:
            return fut.result(timeout)
        except TimeoutError:
            fut.cancel()
            raise

    def _spawn(self, coro):
        """Fire-and-forget a coroutine on the io loop from any thread.
        Connection loss is swallowed (fire-and-forget messages racing
        shutdown are expected)."""

        async def _quiet():
            try:
                await coro
            except (rpc.ConnectionLost, rpc.RpcError):
                pass

        if threading.current_thread() is self._thread:
            self._loop.create_task(_quiet())
        else:
            asyncio.run_coroutine_threadsafe(_quiet(), self._loop)

    # ---- startup -------------------------------------------------------
    def connect(self):
        self._run(self._connect(), timeout=cfg.rpc_connect_timeout_s + 5)

    async def _connect(self):
        # partition plane: drivers and workers share their node's
        # logical endpoint (first writer wins — worker_main labels
        # worker processes before this runs)
        from ray_tpu.common import faults as _faults

        _faults.set_local_endpoint(self.node_id)
        # Reconnecting channel: survives GCS restarts (the GCS restores
        # its tables from the checkpoint; we re-register our identity).
        self.gcs = rpc.ReconnectingConnection(
            self.gcs_address, self._gcs_handler, name=f"{self.mode}->gcs",
            on_reconnect=self._reattach_gcs,
            peer_endpoint="gcs",
        )
        self.raylet = await rpc.connect(
            self.raylet_address, name=f"{self.mode}->raylet",
            peer_endpoint=self.node_id,
        )
        await self.gcs.call(
            "register_worker",
            {"worker_id": self.worker_id.binary(), "node_id": self.node_id},
        )
        # node-event subscription (health plane): a "dead" event closes
        # our conns to that node's workers.  Under a silent partition a
        # TCP conn to a dead node never breaks on its own — pushes would
        # blackhole forever — and after the partition HEALS, a stale
        # conn could reach a zombie worker the GCS already replaced
        # (split-brain).  The GCS's death verdict is the authority;
        # closing the conn routes the actor pump through get_actor to
        # the replacement.  setdefault: a user subscribe("nodes") (the
        # serve controller) replaces the callback, not the subscription;
        # _gcs_handler runs our internal hook regardless.
        self._subscriptions.setdefault("nodes", _ignore_pubsub)
        await self.gcs.call("subscribe", {"channel": "nodes"})
        if self.mode == "driver":
            reply = await self.gcs.call("register_job", {"pid": os.getpid()})
            self.job_id = JobID(reply["job_id"])
        self._metrics_task = self._loop.create_task(self._metrics_push_loop())

    async def _metrics_push_loop(self):
        """Ship this process's util.metrics registry to the GCS
        periodically (ray: stats exporter role)."""
        from ray_tpu.util import metrics as metrics_mod

        while not self._closed:
            await asyncio.sleep(cfg.metrics_push_interval_s)
            snap = metrics_mod.registry_snapshot()
            if not snap:
                continue
            try:
                await self.gcs.notify(
                    "metrics_push",
                    {"reporter": self.worker_id.hex(), "metrics": snap},
                )
            except Exception:
                pass

    async def _reattach_gcs(self, conn):
        await conn.call(
            "register_worker",
            {"worker_id": self.worker_id.binary(), "node_id": self.node_id},
        )
        self._subscriptions.setdefault("nodes", _ignore_pubsub)
        if self.mode == "driver" and self.job_id is not None:
            await conn.call(
                "register_job",
                {"pid": os.getpid(), "job_id": self.job_id.binary()},
            )
        for channel in list(self._subscriptions):
            await conn.call("subscribe", {"channel": channel})

    def _on_node_event_internal(self, msg: dict) -> None:
        """io-loop hook for GCS "nodes" events: when a node is declared
        DEAD, close every cached conn labeled with it.  The close fails
        pending pushes with ConnectionLost, so the actor pump requeues
        and re-resolves through get_actor — landing on the restarted
        actor instead of blackholing into (or, post-heal, split-braining
        with) the dead node's zombie workers."""
        if msg.get("event") != "dead":
            return
        nid = msg.get("node_id")
        if not nid:
            return
        for aid, conn in list(self._actor_conns.items()):
            if conn.peer_endpoint == nid and not conn.closed:
                self._actor_conns.pop(aid, None)
                self._loop.create_task(conn.close())
        for addr, conn in list(self._worker_conns.items()):
            if conn.peer_endpoint == nid and not conn.closed:
                self._loop.create_task(conn.close())

    def _job_hex(self) -> Optional[str]:
        """Job attribution for specs: the driver's own job, or (in a
        worker) the job of the task that last ran here."""
        if self.job_id is not None:
            return self.job_id.hex()
        return self._current_job_hex

    def subscribe(self, channel: str, callback) -> None:
        """Register a pubsub callback (runs on the io loop) and subscribe
        at the GCS; survives GCS restarts via _reattach_gcs."""
        self._subscriptions[channel] = callback
        self._run(self.gcs.call("subscribe", {"channel": channel}))

    async def subscribe_async(self, channel: str, callback) -> None:
        """Loop-native twin of subscribe() for callers already ON the io
        loop (an actor's async method — e.g. the serve proxies
        subscribing to route-version bumps); `_run` from the loop would
        deadlock."""
        self._subscriptions[channel] = callback
        await self.gcs.call("subscribe", {"channel": channel})

    def publish(self, channel: str, message: dict) -> None:
        """Fire-and-forget publish from any thread."""
        self._spawn(
            self.gcs.notify("publish", {"channel": channel, "message": message})
        )

    async def _gcs_handler(self, conn, method, payload):
        # GCS-initiated pushes (actor restarts target workers; pubsub)
        if method == "publish":
            if payload.get("channel") == "nodes":
                # internal health-plane hook, independent of whatever
                # user callback holds the channel slot
                try:
                    self._on_node_event_internal(payload["message"])
                except Exception:
                    logger.exception("node-event hook failed")
            cb = self._subscriptions.get(payload.get("channel"))
            if cb is not None:
                try:
                    cb(payload["message"])
                except Exception:
                    logger.exception(
                        "pubsub callback for %r failed", payload.get("channel")
                    )
            return True
        if method == "exit_worker":
            logger.info("worker told to exit: %s", payload.get("reason"))
            threading.Thread(target=_delayed_exit, daemon=True).start()
            return True
        if method == "create_actor" and self._worker_server is not None:
            return await self._worker_server.handle_create_actor(payload)
        if method == "checkpoint_actor" and self._worker_server is not None:
            return await self._worker_server.handle_checkpoint_actor(payload)
        if method == "checkpoint_abort" and self._worker_server is not None:
            return await self._worker_server.handle_checkpoint_abort()
        if method == "dump_stacks" and self._worker_server is not None:
            return await self._worker_server._handle(conn, "dump_stacks",
                                                     payload)
        raise rpc.RpcError(f"unexpected GCS push {method!r}")

    _worker_server = None  # set by worker_main for GCS-initiated actor creation

    def shutdown(self):
        if self._closed:
            return
        self._closed = True

        async def _close():
            # windowed object notifies (announces, frees) must not die in
            # the buffer — other processes may hold refs to the objects
            self._flush_gcs_notify()
            t = getattr(self, "_metrics_task", None)
            if t is not None:
                t.cancel()
            # resident actor pumps park on their wake events; release
            # them cleanly instead of tearing the loop down under them
            for st in self._actor_states.values():
                st.dead = True
                if st.wake is not None:
                    st.wake.set()
            await asyncio.sleep(0)
            for c in list(self._worker_conns.values()):
                await c.close()
            for c in list(self._actor_conns.values()):
                await c.close()
            if self.gcs:
                await self.gcs.close()
            if self.raylet:
                await self.raylet.close()
            # let cancelled recv loops finalize before the loop stops
            await asyncio.sleep(0.05)

        try:
            self._run(_close(), timeout=5)
        except Exception:
            pass
        from ray_tpu.util.profiling import dump_profile

        dump_profile()
        self.store.close()
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=2)
        set_runtime(None)

    # ---- serialization with ref promotion ------------------------------
    def _reduce_ref(self, ref: ObjectRef):
        """Custom reducer: a ref escaping this process must be resolvable
        anywhere → promote its value to the shared store first."""
        self.ensure_shared(ref.object_id)
        sink = getattr(self._nested_ref_sink, "sink", None)
        if sink is not None:
            sink.append(ref.object_id.binary())
        return (ObjectRef, (ref.object_id, self.node_id))

    def _serialize_tracked(self, value):
        """Serialize, collecting any ObjectRefs nested inside the value —
        the caller registers parent→child edges with the GCS so a stored
        object keeps its borrowed children alive (reference: borrowing,
        reference_count.h — collapsed to GCS-tracked object→object pins)."""
        sink: List[bytes] = []
        self._nested_ref_sink.sink = sink
        try:
            s = self._serialization.serialize(value)
        finally:
            self._nested_ref_sink.sink = None
        return s, sink

    def _register_edges(self, parent_oid: bytes, children: List[bytes]):
        if children and self.gcs and not self.gcs.closed:
            self._gcs_object_notify(
                "ref_edge", {"parent": parent_oid, "children": children}
            )

    def serialize(self, value) -> ser.SerializedObject:
        return self._serialization.serialize(value)

    def deserialize(self, data) -> Any:
        return self._serialization.deserialize(data)

    def _reregister_if_deferred(self, oid: bytes) -> None:
        """A ref whose GCS registration was skipped as local-only is
        escaping: register this process as holder after all."""
        with self._ref_lock:
            if oid in self._deferred_reg:
                self._deferred_reg.discard(oid)
                if (
                    self._local_refs.get(oid, 0) > 0
                    or self._task_holds.get(oid, 0) > 0
                ):
                    self._ref_registered.add(oid)
                    self._pending_ref_add.add(oid)
                    self._schedule_ref_flush()

    def ensure_shared(self, object_id: ObjectID) -> None:
        """Make the object resolvable cluster-wide (idempotent)."""
        oid = object_id.binary()
        # Escape-in-progress marker BEFORE the (possibly slow: spill
        # retries) promotion below: the ref flush must not classify this
        # oid as local-only mid-promotion and silently drop our holder
        # registration.  Ordered before _reregister_if_deferred so a
        # deferral that raced us earlier is cured and none can follow.
        self._escaped.add(oid)
        self._reregister_if_deferred(oid)
        # ref export: any windowed location announce (e.g. this object's
        # own put()) must be GCS-visible before the ref can reach a
        # process that would look it up
        self.flush_object_notifies()
        if oid in self._shared or self.store.contains(oid):
            self._shared.add(oid)
            return
        # The reply applier (io thread) can land the value and pop the result
        # future at any point between our checks — so check, mark, re-check.
        while True:
            if oid in self.memory_store:
                value = self.memory_store[oid]
                if not isinstance(value, _RaiseOnGet):
                    s, nested = self._serialize_tracked(value)
                    self._write_to_store(oid, s)
                    self._register_edges(oid, nested)
                return
            if oid in self._escaped:
                return  # marked; the reply applier will promote on arrival
            if oid in self.result_futures:
                # producing task still in flight from this process: promote
                # its result the moment the reply arrives (re-check in case
                # it landed while we marked)
                self._escaped.add(oid)
                continue
            if oid in self.memory_store:
                # the applier stores the value before popping the future, so
                # a futures-miss for an object of ours means the value is
                # here now — loop back to promote it
                continue
            # Not local: a borrowed ref whose value lives elsewhere already.
            self._shared.add(oid)
            return

    def _write_to_store(self, oid: bytes, s: ser.SerializedObject,
                        urgent_announce: bool = True) -> int:
        """Vectored single-pass put (data plane v2): reserve the arena
        allocation FIRST (exact size — the serialize pass already ran
        without touching payload bytes: large buffers ride the pickle5
        out-of-band protocol as views), then write header + metadata +
        payload buffers straight into the reservation.  Each payload byte
        is copied exactly once; no intermediate bytes is ever built
        (pinned by serialization.COPY_TRACE).  Small payloads land in the
        pre-faulted inline slab; commit() applies the primary-copy flag
        atomically with the seal/publish."""
        size = s.total_bytes
        try:
            buf = self._spill_retry(
                lambda: self.store.reserve(oid, size), size)
        except ObjectExistsError:
            self._shared.add(oid)
            return size
        try:
            s.write_into(buf)
        except BaseException:
            self.store.abort(oid)
            raise
        try:
            # primary copy: the protect flag lands atomically with the
            # seal/publish (seal2), so there is no window where a sealed
            # primary is LRU prey — spilling stays the only sanctioned
            # way out of the arena for a primary.  commit can ALSO hit a
            # packed arena (a slab publish whose shard sub-table is full
            # falls back to the evicting create path); the slab
            # reservation survives that failure, so it rides the same
            # spill-and-retry as reserve.
            self._spill_retry(
                lambda: self.store.commit(oid, protect=True), size)
        except ObjectExistsError:
            # a concurrent writer of the same oid won the publish race
            # (e.g. two threads promoting one escaped result); their copy
            # is the primary
            self._shared.add(oid)
            return size
        self._shared.add(oid)
        self._gcs_object_notify(
            "add_object_location",
            {
                "object_id": oid,
                "node_id": bytes.fromhex(self.node_id),
                "size": size,
            },
            urgent=urgent_announce,
        )
        return size

    def _spill_retry(self, attempt, size: int):
        """Run an arena write step, spilling and retrying on a packed
        arena (StoreFullError): give back any idle inline-slab slots,
        then ask the raylet to spill LRU primaries to disk and retry.
        Escalating requests ride out fragmentation (freed regions merge
        only when adjacent) and concurrent writers racing us to the freed
        space; the bounded patience window rides out a busy raylet whose
        spill pass (fsync per object) is slow under load — failing a task
        because disk IO lagged is worse than waiting.  Only caller/
        executor threads wait; the io loop (which cannot block) keeps the
        single-attempt behavior."""
        try:
            return attempt()
        except StoreFullError:
            self.store.shrink_slab()
            on_loop = threading.current_thread() is self._thread
            deadline = time.monotonic() + (0 if on_loop else 60.0)
            mult = 1  # exact size first: a near-arena-sized object must
            #           not escalate past capacity (the raylet clamps, but
            #           requesting precisely what fits spills the least)
            while True:
                requested = self._request_spill(size * mult,
                                                object_bytes=size)
                try:
                    return attempt()
                except StoreFullError:
                    if requested is None:
                        raise  # no raylet to ask: patience is futile
                    if time.monotonic() >= deadline:
                        raise
                    mult = min(mult + 1, 6)
                    time.sleep(0.25)

    def _request_spill(self, needed_bytes: int,
                       object_bytes: int = 0):
        """Ask our raylet to spill primaries so a create can proceed.

        Returns None when requesting is IMPOSSIBLE (no raylet, raylet
        gone, or called on the io loop, which must not block) — callers
        stop retrying; True/False report whether the pass freed bytes."""
        if self.raylet is None or getattr(self.raylet, "closed", True):
            return None
        if threading.current_thread() is self._thread:
            return None
        try:
            freed = self._run(
                self.raylet.call(
                    "spill_now",
                    {"needed_bytes": needed_bytes,
                     "object_bytes": object_bytes},
                ),
                timeout=30,
            )
            return bool(freed)
        except Exception:
            return None

    # ---- puts / gets ---------------------------------------------------
    def put(self, value) -> ObjectRef:
        self._put_index += 1
        object_id = ObjectID.for_put(self.worker_id, self._put_index)
        oid = object_id.binary()
        s, nested = self._serialize_tracked(value)
        # windowed announce: nothing cluster-side can look this oid up
        # until the ref escapes, and every escape path flushes the window
        self._write_to_store(oid, s, urgent_announce=False)
        self._register_edges(oid, nested)
        return ObjectRef(object_id, self.node_id)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"ray_tpu.get expects ObjectRef(s), got {type(r)}")
        deadline = None if timeout is None else time.monotonic() + timeout
        # Fast path: locally-produced inline task results resolve on the
        # caller thread with a direct wakeup from the reply applier — no
        # coroutine scheduling, no extra io-loop iterations.  Any ref the
        # fast path can't serve (shm-stored, remote, reconstruction) drops
        # the remainder onto the full async path.
        out = []
        # reusable per-thread wait Event: a thread waits on one oid at a
        # time and always deregisters before moving on, so pure
        # memory-store hits never allocate and windowed gets share one
        ev = getattr(self._sync_get_tls, "ev", None)
        if ev is None:
            ev = self._sync_get_tls.ev = threading.Event()
        for r in refs:
            oid = r.object_id.binary()
            v = self._try_sync_get(oid, deadline, ev)
            if v is _SYNC_MISS:
                # local shm hit: read directly on the caller thread — the
                # arena is process-shared-mutex guarded, deserialize is
                # pure, so no io-loop round trip is needed (ray: plasma
                # client reads mmap'd objects without the core worker)
                if oid not in self.result_futures:
                    value, found = self._read_from_store(oid)
                    if found:
                        out.append(value)
                        continue
                break
            out.append(v)
        if len(out) < len(refs):
            out.extend(self._run(
                self._get_async(
                    [r.object_id.binary() for r in refs[len(out):]], deadline
                ),
                timeout=None,
            ))
        return out[0] if single else out

    def _try_sync_get(self, oid: bytes, deadline, ev=None):
        """Resolve a locally-produced inline task result without touching
        the io loop.  Lock-free: correctness rides on the reply applier's
        write order (value into memory_store BEFORE the result future is
        popped and waiters are signalled) plus a re-check after waiter
        registration, so a completion racing the registration can never
        strand the caller.  Returns _SYNC_MISS for anything that needs the
        shm store or a remote pull.  ``ev`` is an optional reusable wait
        Event (a windowed get would otherwise allocate one per ref)."""
        while True:
            if oid in self.memory_store:
                value = self.memory_store[oid]
                if isinstance(value, _RaiseOnGet):
                    raise value.exc
                return value
            if oid not in self.result_futures:
                return _SYNC_MISS
            if ev is None:
                ev = threading.Event()
            else:
                ev.clear()
            with self._sync_reg_lock:
                # single-waiter fast shape: the Event itself; upgraded
                # to a list only under contention on one oid
                cur = self._sync_waiters.get(oid)
                if cur is None:
                    self._sync_waiters[oid] = ev
                elif isinstance(cur, list):
                    cur.append(ev)
                else:
                    self._sync_waiters[oid] = [cur, ev]
            # re-check: the reply may have been applied between the checks
            # above and the registration, in which case its signal pass
            # could have missed our event
            if oid in self.memory_store or oid not in self.result_futures:
                self._drop_sync_waiter(oid, ev)
                continue
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                ok = False
            else:
                ok = ev.wait(remaining)
            self._drop_sync_waiter(oid, ev)
            if not ok:
                raise GetTimeoutError(
                    f"timed out waiting for {oid.hex()[:16]}"
                )

    def _drop_sync_waiter(self, oid: bytes, ev):
        with self._sync_reg_lock:
            ws = self._sync_waiters.get(oid)
            if ws is ev:
                # drop the empty entry (it would otherwise leak: the
                # one-shot signal for this oid may already have fired)
                self._sync_waiters.pop(oid, None)
            elif isinstance(ws, list):
                try:
                    ws.remove(ev)
                except ValueError:
                    pass
                if not ws:
                    self._sync_waiters.pop(oid, None)

    def _signal_sync_waiters(self, oid: bytes):
        ws = self._sync_waiters.pop(oid, None)
        if ws is None:
            return
        if isinstance(ws, list):
            # snapshot: a timed-out caller may remove() concurrently, and
            # iterating the live list under a remove can skip a waiter
            for ev in list(ws):
                ev.set()
        else:
            ws.set()

    # ---- streaming generator returns -----------------------------------
    # Reference: num_returns="streaming" + ObjectRefGenerator
    # (python/ray/_raylet.pyx:273, remote_function.py:343-349).  The
    # producing worker ships each yielded item as a `stream_item` notify
    # over the same duplex connection that carried the push; the final RPC
    # reply closes the stream with the item count.  Consumption acks feed
    # credit-based backpressure on the producer.

    async def _worker_inbound(self, conn, method: str, p: Any):
        """Inbound messages on caller->worker connections."""
        if method == "stream_item":
            self._deliver_stream_item(conn, p)
            return True
        sub = self._rpc_subhandlers.get(method)
        if sub is not None:
            return await sub(conn, p)
        raise rpc.RpcError(f"unexpected inbound {method!r} on worker conn")

    # ---- subsystem RPC + peer channels ---------------------------------
    def register_rpc_handler(self, method: str, handler) -> None:
        """Claim an RPC method name for a subsystem.  ``handler`` is an
        ``async (conn, payload) -> result`` invoked on the io loop for
        every inbound request/notify carrying that method (on the worker
        server and on caller→worker connections alike)."""
        existing = self._rpc_subhandlers.get(method)
        if existing is not None and existing is not handler:
            raise ValueError(f"rpc method {method!r} already registered")
        self._rpc_subhandlers[method] = handler

    def add_peer_close_watcher(self, cb) -> None:
        """Observe worker-peer connection closures (io loop callback)."""
        if cb not in self._peer_close_watchers:
            self._peer_close_watchers.append(cb)

    def _notify_peer_closed(self, conn) -> None:
        for cb in list(self._peer_close_watchers):
            try:
                cb(conn)
            except Exception:
                logger.exception("peer close watcher failed")

    async def peer_connection(self, addr: str) -> rpc.Connection:
        """Peer channel acquisition: a (cached) duplex connection to
        another worker's RPC server, usable from inside actors for
        direct worker↔worker traffic (the runtime-collective data
        plane).  Shares the cache with the task-dispatch path, so a
        collective group and a task stream to the same peer ride one
        TCP connection."""
        return await self._connect_worker(addr)

    async def peer_connection_to(self, addr: str,
                                 node_hex: Optional[str] = None):
        """peer_connection with the peer's node identity, so the conn is
        labeled for the partition plane (collective backends know their
        members' nodes; plain addr callers keep the unlabeled form)."""
        return await self._connect_worker(addr, node_hex)

    def _deliver_stream_item(self, conn, p: dict):
        tid = p["task_id"]
        buf = self._streams.get(tid)
        if buf is None:
            return  # stream abandoned/cancelled: drop silently
        idx = p["index"]
        kind, payload = p["item"]
        oid = ObjectID.for_task_return(TaskID(tid), idx).binary()
        if kind == "inline":
            self.memory_store[oid] = self._serialization.deserialize(payload)
        elif kind == "err":
            self.memory_store[oid] = _RaiseOnGet(
                self._serialization.deserialize(payload)
            )
        # kind == "stored": resolvable via the shm/pull path
        buf.deliver(idx, conn)
        if buf.cancel_state == 1:
            # cancel arrived before we knew the producing connection
            buf.cancel_state = 2
            self._spawn(conn.notify("cancel_task", {"task_id": tid}))

    def stream_next(self, tid: bytes, timeout: Optional[float] = None):
        """Block until the next stream item is available; returns its
        ObjectRef (which may raise on get for an error item).  Raises
        StopIteration when the stream is exhausted."""
        buf = self._streams.get(tid)
        if buf is None:
            raise StopIteration
        deadline = None if timeout is None else time.monotonic() + timeout
        with buf.cond:
            while True:
                idx = buf.next_idx
                if idx in buf.items:
                    buf.items.discard(idx)
                    buf.next_idx = idx + 1
                    conn = buf.conn
                    break
                if buf.count is not None and idx >= buf.count:
                    if not buf.items:
                        self._streams.pop(tid, None)
                    raise StopIteration
                if buf.failed is not None:
                    raise buf.failed
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"timed out waiting for stream item {idx}"
                    )
                buf.cond.wait(remaining)
        oid = ObjectID.for_task_return(TaskID(tid), idx)
        if conn is not None and not conn.closed:
            self._spawn(conn.notify("stream_ack", {"task_id": tid, "upto": idx}))
        return ObjectRef(oid)

    async def stream_next_async(self, tid: bytes):
        """Async variant of stream_next.

        Loop-native when awaited on the runtime's own io loop (async
        actor methods, serve replicas/proxies): NO thread is parked per
        in-flight stream — the delivery path sets an asyncio.Event.
        Elsewhere it falls back to a worker thread."""
        try:
            on_loop = asyncio.get_running_loop() is self._loop
        except RuntimeError:
            on_loop = False
        if not on_loop:
            try:
                return await asyncio.to_thread(self.stream_next, tid)
            except StopIteration:
                raise StopAsyncIteration from None
        # exhaustion raises StopAsyncIteration (PEP 479: a coroutine must
        # not let StopIteration escape)
        buf = self._streams.get(tid)
        if buf is None:
            raise StopAsyncIteration
        while True:
            with buf.cond:
                idx = buf.next_idx
                if idx in buf.items:
                    buf.items.discard(idx)
                    buf.next_idx = idx + 1
                    conn = buf.conn
                    break
                if buf.count is not None and idx >= buf.count:
                    if not buf.items:
                        self._streams.pop(tid, None)
                    raise StopAsyncIteration
                if buf.failed is not None:
                    raise buf.failed
                buf.aev = asyncio.Event()
                ev = buf.aev
            await ev.wait()
        oid = ObjectID.for_task_return(TaskID(tid), idx)
        if conn is not None and not conn.closed:
            self._spawn(
                conn.notify("stream_ack", {"task_id": tid, "upto": idx})
            )
        return ObjectRef(oid)

    def stream_cancel(self, tid: bytes) -> bool:
        """Stop a streaming producer; the consumer's next() receives a
        TaskCancelledError ref once the worker acknowledges (or drains)."""
        buf = self._streams.get(tid)
        if buf is None:
            return False
        conn = buf.conn
        if conn is not None and not conn.closed:
            buf.cancel_state = 2
            self._spawn(conn.notify("cancel_task", {"task_id": tid}))
        else:
            # Either not dispatched yet (the pre-push flag catches it) or
            # pushed but no item delivered yet — mark the buf so the first
            # delivery forwards the cancel to the producing worker.
            buf.cancel_state = 1
            self._cancel_requested.add(
                ObjectID.for_task_return(TaskID(tid), 0).binary()
            )
        return True

    def stream_abandon(self, tid: bytes):
        """Consumer dropped the generator: cancel production, release any
        undelivered buffered items."""
        buf = self._streams.pop(tid, None)
        if buf is None:
            return
        with buf.cond:
            pending = list(buf.items)
            conn = buf.conn
            consumed_upto = buf.next_idx
        for idx in pending:
            oid = ObjectID.for_task_return(TaskID(tid), idx).binary()
            self.memory_store.pop(oid, None)
        if buf.count is None and buf.failed is None:
            # still producing: the closing reply frees the stored tail
            # (see _apply_task_reply) and the worker gets a cancel
            self._abandoned_streams[tid] = consumed_upto
            if conn is not None and not conn.closed:
                self._spawn(conn.notify("cancel_task", {"task_id": tid}))
        elif buf.count is not None and buf.count > consumed_upto:
            # producer already finished: free the stored tail now
            oids = [
                ObjectID.for_task_return(TaskID(tid), i).binary()
                for i in range(consumed_upto, buf.count)
            ]
            if self.gcs and not self.gcs.closed:
                self._gcs_object_notify("free_objects", {"object_ids": oids})

    async def await_ref(self, ref: ObjectRef):
        (value,) = await self._get_async([ref.object_id.binary()], None)
        return value

    def as_future(self, ref: ObjectRef):
        """concurrent.futures.Future resolving to the object's VALUE
        (not the one-element batch list `_get_async` returns) — the
        thread-safe bridge for awaiting a ref from outside the runtime
        loop (ObjectRef.future(), serve's loop-agnostic result_async)."""

        async def _one():
            (value,) = await self._get_async(
                [ref.object_id.binary()], None
            )
            return value

        return asyncio.run_coroutine_threadsafe(_one(), self._loop)

    async def _get_async(self, oids: List[bytes], deadline) -> List[Any]:
        results: Dict[bytes, Any] = {}
        for oid in oids:
            if oid not in results:
                results[oid] = await self._resolve_one(oid, deadline)
        return [results[oid] for oid in oids]

    async def _worker_death_detail(self, worker_id) -> str:
        """Ask the GCS why a worker died (e.g. the memory monitor killed
        it).  The raylet's death notification races our ConnectionLost,
        so poll briefly; empty string when nothing is recorded."""
        wid = (
            worker_id.binary() if hasattr(worker_id, "binary") else worker_id
        )
        for _ in range(4):
            try:
                info = await asyncio.wait_for(
                    self.gcs.call("get_worker_death_info",
                                  {"worker_id": wid}),
                    timeout=2.0,
                )
                if info.get("reason"):
                    return f" ({info['reason']})"
            except Exception:
                return ""
            await asyncio.sleep(0.5)
        return ""

    def _result_future(self, oid: bytes):
        """Loop-only: the real asyncio.Future for a locally-pending
        result, upgrading the lazy _PENDING_RESULT marker on first async
        need.  None when the result is not pending here."""
        fut = self.result_futures.get(oid)
        if fut is _PENDING_RESULT:
            fut = self.result_futures[oid] = asyncio.Future(loop=self._loop)
        return fut

    async def await_ref_completion(self, ref: ObjectRef) -> None:
        """Wait until the task producing ``ref`` has COMPLETED, without
        fetching its value — bookkeeping callers (e.g. serve's chained
        in-flight accounting) must not materialize a possibly-huge
        result into this process just to observe that it finished."""
        fut = self._result_future(ref.object_id.binary())
        if fut is not None:
            try:
                await asyncio.shield(fut)
            except Exception:
                pass  # errored completion still counts as completed

    async def _resolve_one(self, oid: bytes, deadline) -> Any:
        failed_pulls = 0
        pull_backoff = None  # built lazily: only failed pulls pay for it
        last_pull_exc = None  # chained into ObjectLostError for diagnosis
        while True:
            if oid in self.memory_store:
                value = self.memory_store[oid]
                if isinstance(value, _RaiseOnGet):
                    raise value.exc
                return value
            # a task from this process produces it → wait for completion
            fut = self._result_future(oid)
            if fut is not None:
                remaining = (
                    None
                    if deadline is None or deadline == float("inf")
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"timed out waiting for {oid.hex()[:16]}")
                try:
                    await asyncio.wait_for(
                        asyncio.shield(fut),
                        timeout=remaining,
                    )
                except asyncio.TimeoutError:
                    raise GetTimeoutError(
                        f"timed out waiting for {oid.hex()[:16]}"
                    ) from None
                continue  # completed: value now in memory store or shm
            # shared store path
            value, found = self._read_from_store(oid)
            if found:
                return value
            # local get-miss: flush any windowed announces before asking
            # the cluster (the object may be one whose announce is still
            # sitting in our own window)
            self.flush_object_notifies()
            # ask raylet to pull it from another node
            remaining = 30.0 if deadline is None else deadline - time.monotonic()
            if remaining <= 0:
                raise GetTimeoutError(f"timed out resolving {oid.hex()[:16]}")
            try:
                ok = await self.raylet.call(
                    "pull_object",
                    {"object_id": oid, "timeout": min(remaining, 30.0)},
                    timeout=min(remaining, 30.0) + 10,
                )
            except rpc.ConnectionLost as e:
                # our raylet is gone: there is no pull plane left to
                # retry against — fall through to reconstruction/loss
                ok = False
                last_pull_exc = e
            except (rpc.RemoteCallError, rpc.RpcError,
                    asyncio.TimeoutError) as e:
                # a transient pull-plane failure (raylet handler error,
                # rpc budget exceeded under load, an injected recv
                # fault) is a FAILED PULL, not object loss — it rides
                # the same bounded retry budget as a "retry" verdict
                ok = "retry"
                last_pull_exc = e
            if not ok or ok == "retry":
                # last chance: it may have landed locally while we pulled
                value, found = self._read_from_store(oid)
                if found:
                    return value
                failed_pulls += 1
                if ok == "retry" and failed_pulls < cfg.pull_retry_max:
                    # a copy exists (spill file / live peer) but this
                    # round's restore or transfer failed — transient
                    # arena pressure, NOT object loss; back off and retry
                    # (shared policy; a lapsed deadline surfaces at the
                    # loop head as GetTimeoutError)
                    if pull_backoff is None:
                        pull_backoff = Backoff(
                            BackoffPolicy(
                                base_s=cfg.pull_retry_base_s,
                                mult=cfg.backoff_mult,
                                max_s=cfg.pull_retry_max_s,
                                jitter_frac=cfg.backoff_jitter_frac,
                            ),
                            deadline=deadline,
                        )
                    await pull_backoff.wait()
                    continue
                # A failed pull already waited a location round: if we own
                # lineage for the object, re-execute its producing task now
                # (reference: object_recovery_manager.h:41) — whatever the
                # deadline shape, recovery beats spinning.
                if await self._try_reconstruct(oid):
                    continue
                if deadline is None or (
                    deadline == float("inf")
                    and failed_pulls >= cfg.pull_retry_infinite_max
                ):
                    # no-timeout get fails fast; an infinite-deadline wait
                    # (ray_tpu.wait) retries a few ~30s location rounds so
                    # an in-flight cross-owner ref isn't misreported, then
                    # surfaces genuinely lost objects as errored (= ready)
                    # chain the last pull-plane error (when there was
                    # one): a persistent raylet handler failure must not
                    # masquerade as plain object loss
                    raise ObjectLostError(
                        f"object {oid.hex()[:16]} not found anywhere in "
                        f"the cluster"
                        + (f" (last pull error: {last_pull_exc!r})"
                           if last_pull_exc is not None else "")
                    ) from last_pull_exc
                await asyncio.sleep(cfg.get_retry_poll_s)  # retry until deadline

    def _read_from_store(self, oid: bytes) -> Tuple[Any, bool]:
        pin = self.store.get(oid)
        if pin is None:
            return None, False
        if (
            pin.view.nbytes >= self._zerocopy_threshold
            and self.store.pin_headroom() > 64
            and ser.SUPPORTS_ZEROCOPY_OWNER
        ):
            # Zero-copy: deserialize straight off the arena; the pin's
            # lifetime rides the returned object's buffer-base chain
            # (serialization._OwnedBuffer), exactly plasma's mmap-read
            # semantics.  Read-only so a caller can't scribble on shm.
            # The pin is deliberately NOT released here — it unpins when
            # the last deserialized view is garbage-collected.
            try:
                value = self._serialization.deserialize(
                    pin.view.toreadonly(), owner=pin
                )
            except BaseException:
                # On failure nothing chains the pin; a retained exception
                # (logging, sys.last_exc) would otherwise keep the arena
                # range pinned for as long as the traceback lives.
                pin.release()
                raise
            return value, True
        try:
            # small objects (and pin-ledger pressure — many large results
            # already held zero-copy): a copy is cheaper than holding a
            # pin that blocks LRU eviction for the value's whole lifetime
            value = self._serialization.deserialize(bytes(pin.view))
        finally:
            pin.release()
        return value, True

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        deadline = None if timeout is None else time.monotonic() + timeout
        return self._run(self._wait_async(refs, num_returns, deadline))

    async def _wait_async(self, refs, num_returns, deadline):
        pending = list(refs)
        ready: List[ObjectRef] = []
        # Per-ref resolution runs with an INFINITE deadline: the wait
        # timeout is enforced by asyncio.wait below.  A real deadline here
        # would complete futures with GetTimeoutError at the cutoff and
        # misreport timed-out refs as ready; deadline=None would convert a
        # slow cross-owner pull into ObjectLostError (also "ready").  inf
        # keeps retrying the pull until the ref truly resolves or errors.
        futs = {
            r: asyncio.ensure_future(
                self._resolve_one(r.object_id.binary(), float("inf"))
            )
            for r in pending
        }
        try:
            while len(ready) < num_returns:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                done, _ = await asyncio.wait(
                    [futs[r] for r in pending],
                    timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done:
                    break
                for r in list(pending):
                    if futs[r].done():
                        pending.remove(r)
                        ready.append(r)
                        if futs[r].exception():
                            pass  # errored objects count as ready (ray semantics)
        finally:
            for r in pending:
                futs[r].cancel()
        return ready, pending

    # ---- task submission ----------------------------------------------
    def fn_hash_and_register(self, fn) -> bytes:
        # Memoized per function OBJECT: cloudpickling the (identical)
        # function on every submit cost ~90µs/call — the whole hash
        # exists so the function ships once.  Semantics note (same as
        # the reference's once-per-export function shipping): the code
        # and its captured state are SNAPSHOTTED at a function object's
        # first submit; mutating a captured cell between submits of the
        # same object is not re-shipped.  A NEW function object (fresh
        # lambda/def) always re-pickles.
        #
        # The identity check rides a WeakValueDictionary: a dead
        # function's entry vanishes, so a recycled id() can never alias
        # a DIFFERENT function to a stale hash, and per-submit lambdas
        # (with whatever their closures capture) are not pinned alive.
        entry = self._fn_hash_memo.get(id(fn))
        if entry is not None and entry[0]() is fn:
            # single-entry read: (weakref, hash) read atomically, so a
            # concurrent submit clearing the memo can't strand us between
            # an identity check and a separate hash lookup
            return entry[1]
        blob = cloudpickle.dumps(fn)
        h = hashlib.blake2b(blob, digest_size=16).digest()
        if h not in self._fn_cache:
            self._fn_cache[h] = fn
            self._spawn(
                self.gcs.call(
                    "kv_put",
                    {"key": f"fn:{h.hex()}", "value": blob, "overwrite": False},
                )
            )
        if len(self._fn_hash_memo) > 4096:
            self._fn_hash_memo.clear()  # also reaps dead-weakref entries
        try:
            self._fn_hash_memo[id(fn)] = (weakref.ref(fn), h)
        except TypeError:
            pass  # not weakref-able: skip memoization
        return h

    async def resolve_fn(self, fn_hash: bytes):
        fn = self._fn_cache.get(fn_hash)
        if fn is None:
            blob = await self.gcs.call("kv_get", {"key": f"fn:{fn_hash.hex()}"})
            if blob is None:
                raise TaskError("FunctionNotFound", fn_hash.hex(), "", "")
            fn = cloudpickle.loads(blob)
            self._fn_cache[fn_hash] = fn
        return fn

    def _pack_args(self, args, kwargs) -> list:
        """Top-level refs pass by reference; values serialize (promoting any
        nested refs via the reducer)."""
        if not args and not kwargs:
            return ()  # shared empty: no per-call list on no-arg calls
        ser_ctx = self._serialization
        packed = []
        for a in args:
            if isinstance(a, ObjectRef):
                self.ensure_shared(a.object_id)
                packed.append(("ref", a.object_id.binary(), a._owner_hint))
            else:
                # small immutable values (flags, indexes, short strings)
                # repeat across submissions: the memo skips the pickle
                b = ser_ctx.serialize_small(a)
                if b is None:
                    b = ser_ctx.serialize(a).to_bytes()
                packed.append(("val", b))
        for k, v in (kwargs or {}).items():
            if isinstance(v, ObjectRef):
                self.ensure_shared(v.object_id)
                packed.append(("kwref", k, v.object_id.binary(), v._owner_hint))
            else:
                b = ser_ctx.serialize_small(v)
                if b is None:
                    b = ser_ctx.serialize(v).to_bytes()
                packed.append(("kwval", k, b))
        return packed

    def unpack_args_sync(self, packed) -> Optional[Tuple[list, dict]]:
        """Ref-free fast path: pure deserialization, no loop round-trip.
        Returns None when any arg is an ObjectRef (caller must await
        unpack_args on the io loop instead) — the hot actor-call path
        has inline args and skips two thread handoffs per call."""
        if any(item[0] in ("ref", "kwref") for item in packed):
            return None
        args, kwargs = [], {}
        for item in packed:
            if item[0] == "val":
                args.append(self._serialization.deserialize(item[1]))
            else:
                kwargs[item[1]] = self._serialization.deserialize(item[2])
        return args, kwargs

    async def unpack_args(self, packed) -> Tuple[list, dict]:
        args, kwargs = [], {}
        for item in packed:
            kind = item[0]
            if kind == "ref":
                (value,) = await self._get_async([item[1]], None)
                args.append(value)
            elif kind == "val":
                args.append(self._serialization.deserialize(item[1]))
            elif kind == "kwref":
                (value,) = await self._get_async([item[2]], None)
                kwargs[item[1]] = value
            else:
                kwargs[item[1]] = self._serialization.deserialize(item[2])
        return args, kwargs

    def make_task_template(
        self,
        fn,
        *,
        name: str = "",
        num_returns=1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: int = 0,
        strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ) -> TaskTemplate:
        """Build the immutable submission template for one function /
        option-set: function shipping, resource validation, scheduling
        class key, runtime-env normalization and the spec skeleton all
        happen HERE, once — `.remote()` pays only id/arg fills.
        RemoteFunction caches the result per runtime instance."""
        fn_hash = self.fn_hash_and_register(fn)
        # {} is a valid demand (zero-resource tasks, e.g. PG probes)
        resources = dict(resources) if resources is not None else {"CPU": 1}
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 1
            max_retries = 0  # re-running a generator would double-send items
        strategy = dict(strategy) if strategy else {}
        # Scheduling class = (fn, resources, strategy) — like the reference's
        # SchedulingClass (ray: common/task/task_spec.h) — so leased workers
        # are only reused for the same function shape and a slow function
        # can't head-of-line-block unrelated tasks.
        rtenv_desc = self._normalize_runtime_env(runtime_env)
        from ray_tpu.core import runtime_env as rtenv_mod

        class_key = (
            fn_hash,
            tuple(sorted(resources.items())),
            tuple(sorted(strategy.items(), key=lambda kv: kv[0])),
            rtenv_mod.descriptor_key(rtenv_desc),
        )
        if rtenv_desc is not None:
            self._class_runtime_envs[class_key] = rtenv_desc
        # NB: resources deliberately do NOT ride the wire spec — the
        # worker never schedules (the lease already placed the task) and
        # nothing else reads them off the spec; they live on the template
        # and PendingTask for lease requests and lineage re-execution
        skeleton = {
            "task_id": b"",  # filled per call
            "name": name,
            "fn_hash": fn_hash,
            "args": (),      # filled per call
            "num_returns": num_returns,
            "caller_id": self.worker_id.binary(),
        }
        if streaming:
            skeleton["streaming"] = True
        # drivers bake their (constant) job into the skeleton; workers
        # attribute nested submissions to the job of the task that last
        # ran here, which changes — those fill per call
        fill_job = self.job_id is None
        if not fill_job:
            skeleton["job"] = self._job_hex()
        return TaskTemplate(
            self, skeleton, class_key, resources, strategy,
            num_returns, streaming, max_retries, fill_job,
        )

    def submit_task_from_template(self, tmpl: TaskTemplate, args, kwargs):
        """Hot-path submit: fill ids + args against the cached template
        and hand the PendingTask to the io loop through the coalesced
        submit queue.  The spec dict is NOT copied per call — the compact
        wire path ships (tpl_id, task_id, args, job) and the skeleton
        travels to each worker connection once (streaming and tracing
        calls keep the full-dict spec, which both need to annotate).
        Returns a bare ObjectRef for num_returns == 1, a list of refs
        otherwise, an ObjectRefGenerator when streaming."""
        task_id = os.urandom(16)
        packed = self._pack_args(args, kwargs)
        job = self._job_hex() if tmpl.fill_job else None
        spec = None
        if tmpl.streaming or tracing.enabled():
            spec = dict(tmpl.skeleton)
            spec["task_id"] = task_id
            spec["args"] = packed
            if job is not None:
                spec["job"] = job
        n = tmpl.num_returns
        if n == 1:
            return_ids = (task_return_binary(task_id, 0),)
        else:
            return_ids = tuple(
                task_return_binary(task_id, i) for i in range(n)
            )
        # Dependencies this process itself is producing.  They must resolve
        # BEFORE the task may occupy a lease — a worker blocking on an
        # in-flight upstream result while holding the worker that upstream
        # task needs is a scheduling deadlock (reference:
        # LocalDependencyResolver, core_worker/transport/dependency_resolver.h).
        dep_oids = () if not packed else [
            item[1] if item[0] == "ref" else item[2]
            for item in packed
            if item[0] in ("ref", "kwref")
        ]
        pending = PendingTask(
            spec, return_ids, tmpl.max_retries, dep_oids=dep_oids,
            class_key=tmpl.class_key, resources=tmpl.resources,
            strategy=tmpl.strategy, tmpl=tmpl, task_id=task_id,
            args=packed, job=job, streaming=tmpl.streaming,
        )
        self._timeline.append(
            ("submit", tmpl.skeleton["name"], task_id.hex(), time.time(),
             self._pid, None)
        )
        if spec is not None and tracing.enabled():
            # W3C trace context rides the spec; the worker's execute
            # span parents under THIS submit span (reference:
            # _ray_trace_ctx in tracing_helper.py)
            with tracing.span(
                f"submit {tmpl.skeleton['name']}", task_id=task_id.hex()
            ):
                spec["trace_ctx"] = tracing.inject()
        # ref args stay pinned while the task is in flight, even if the
        # caller drops its own refs (reference: task-argument references,
        # reference_count.h)
        if dep_oids:
            self._hold_for_task(dep_oids)
        if tmpl.streaming:
            # stream buffer must exist before any item can arrive; no
            # result futures (items resolve via the memory store / shm),
            # no lineage (generators are not reconstructible)
            self._streams[task_id] = _StreamBuf()
            self._submit_to_loop(pending)
            return ObjectRefGenerator(task_id)
        self._record_lineage(pending)
        # Register result futures before the task can possibly complete,
        # lazily (_PENDING_RESULT upgrades to an asyncio.Future only on
        # async need), and create refs BEFORE the enqueue can run: a fast
        # failure path must see a nonzero refcount or it would drop the
        # error sentinel.
        for oid in return_ids:
            self.result_futures[oid] = _PENDING_RESULT
        if n == 1:
            ref = ObjectRef(ObjectID(return_ids[0]), self.node_id)
            self._submit_to_loop(pending)
            return ref
        refs = [ObjectRef(ObjectID(oid), self.node_id) for oid in return_ids]
        self._submit_to_loop(pending)
        return refs

    def submit_task(
        self,
        fn,
        args,
        kwargs,
        *,
        name: str = "",
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        max_retries: int = 0,
        strategy: Optional[dict] = None,
        runtime_env: Optional[dict] = None,
    ):
        """Untemplated submit (compatibility surface): builds a one-shot
        template.  RemoteFunction bypasses this with a cached template;
        returns a list of refs (or a generator) like it always did."""
        tmpl = self.make_task_template(
            fn, name=name, num_returns=num_returns, resources=resources,
            max_retries=max_retries, strategy=strategy,
            runtime_env=runtime_env,
        )
        out = self.submit_task_from_template(tmpl, args, kwargs)
        if isinstance(out, ObjectRef):
            return [out]
        return out

    # ---- coalesced submission hop --------------------------------------
    def _submit_to_loop(self, task: PendingTask):
        """Hand a PendingTask to the io loop, coalescing the cross-thread
        wakeup: every task appended between two loop ticks drains in one
        scheduled callback."""
        if threading.current_thread() is self._thread:
            self._admit_submitted(task)
            return
        self._submit_q.append(task)
        # deliberately lock-free (GIL-ordered): the drainer clears the
        # flag BEFORE draining, so a submitter reading a stale True has
        # its append covered by that very drain; a stale False only
        # schedules a redundant no-op drain.  A lock here would sit on
        # every submission.
        # rtlint: disable-next=RT108
        if not self._submit_q_scheduled:
            # cross-plane by design: the protocol above makes the
            # caller-side set / loop-side clear safe without a lock
            # rtlint: disable-next=RT301
            self._submit_q_scheduled = True
            self._loop.call_soon_threadsafe(self._drain_submit_q)

    def _drain_submit_q(self):
        # clear the flag BEFORE draining: a submitter appending after the
        # clear schedules a fresh (possibly redundant, never missed) drain
        # (GIL-ordered handshake with _submit_to_loop, audited above)
        # rtlint: disable-next=RT301
        self._submit_q_scheduled = False
        q = self._submit_q
        while q:
            try:
                task = q.popleft()
            except IndexError:
                break
            self._admit_submitted(task)

    def _admit_submitted(self, task: PendingTask):
        if task.spec is not None and "actor_id" in task.spec:
            self._enqueue_actor_task(task)
        else:
            self._enqueue_after_deps(task)

    # ---- flush-window GCS notifications --------------------------------
    def _gcs_object_notify(self, method: str, payload: dict,
                           urgent: bool = True) -> None:
        """Buffer an object-directory notify for the batched flush.
        ``urgent`` events (locations another process may already be
        waiting on) flush this tick; windowed events (e.g. put()
        announces of refs that have not escaped) wait up to
        cfg.gcs_notify_flush_window_s / gcs_notify_flush_max for
        company.  Buffer order is preserved on the wire and applied in
        order by the GCS, so announce-before-free style invariants hold
        within the batch."""
        if self._closed:
            return
        with self._gcs_nbuf_lock:
            self._gcs_nbuf.append((method, payload))
            if len(self._gcs_nbuf) >= cfg.gcs_notify_flush_max:
                urgent = True
            if urgent:
                if self._gcs_nbuf_mode == "soon":
                    return
                self._gcs_nbuf_mode = "soon"
                mode = "soon"
            else:
                if self._gcs_nbuf_mode is not None:
                    return
                self._gcs_nbuf_mode = "timer"
                mode = "timer"
        try:
            if mode == "soon":
                self._loop.call_soon_threadsafe(self._flush_gcs_notify)
            else:
                self._loop.call_soon_threadsafe(self._arm_gcs_notify_timer)
        except RuntimeError:
            pass  # loop closing

    def flush_object_notifies(self) -> None:
        """Flush the object-notify window now (callable from any
        thread).  Every path that can make a windowed announce
        observable to another process — ref export, a directory read,
        an explicit free — calls this first; the flush-window batching
        is then invisible to cross-process visibility semantics."""
        if self._gcs_nbuf:
            self._flush_gcs_notify()

    def _arm_gcs_notify_timer(self):
        # loop-only.  The window may have been upgraded to a tick flush
        # meanwhile; the timer then fires on an empty buffer (no-op).
        self._loop.call_later(
            cfg.gcs_notify_flush_window_s, self._flush_gcs_notify
        )

    def _flush_gcs_notify(self):
        """Send everything buffered as ONE rpc (callable from any
        thread; the send itself always happens on the io loop)."""
        with self._gcs_nbuf_lock:
            items = self._gcs_nbuf
            self._gcs_nbuf_mode = None
            if not items:
                return
            self._gcs_nbuf = []
        if self.gcs is None or self.gcs.closed:
            return
        if len(items) == 1:
            self._spawn(self.gcs.notify(items[0][0], items[0][1]))
        else:
            self._spawn(
                self.gcs.notify("object_notify_batch", {"items": items})
            )

    def _enqueue_after_deps(self, pending: PendingTask):
        """Queue the task once locally-produced ref args have resolved."""
        dep_oids = pending.dep_oids
        waits = [
            fut
            for oid in dep_oids
            if (fut := self._result_future(oid)) is not None
            and not fut.done()
        ]
        if not waits:
            failed = self._failed_dep(dep_oids)
            if failed is not None:
                self._fail_task(pending, failed)
                return
            self._enqueue_task(pending)
            return

        async def wait_then_enqueue():
            await asyncio.gather(
                *(asyncio.shield(f) for f in waits), return_exceptions=True
            )
            failed = self._failed_dep(dep_oids)
            if failed is not None:
                self._fail_task(pending, failed)
            else:
                self._enqueue_task(pending)

        self._loop.create_task(wait_then_enqueue())

    def _failed_dep(self, dep_oids) -> Optional[Exception]:
        """If a locally-owned dependency errored, its error (else None)."""
        for oid in dep_oids:
            value = self.memory_store.get(oid)
            if isinstance(value, _RaiseOnGet):
                return value.exc
        return None

    def _consume_cancel_flag(self, task: PendingTask) -> bool:
        """True (and fails the task) if cancel() flagged it pre-dispatch."""
        if any(oid in self._cancel_requested for oid in task.return_ids):
            for oid in task.return_ids:
                self._cancel_requested.discard(oid)
            self._fail_task(task, TaskCancelledError(task.return_ids[0].hex()))
            return True
        return False

    def _enqueue_task(self, pending: PendingTask):
        if self._consume_cancel_flag(pending):
            return
        class_key = pending.class_key
        st = self._classes.get(class_key)
        if st is None:
            st = self._classes[class_key] = SchedClassState()
        st.queue.append(pending)
        self._pump_class(class_key, pending.resources, pending.strategy)

    def _pump_class(self, class_key, resources, strategy):
        """Dispatch queued tasks onto leased workers; request more leases if
        the queue outruns capacity; give idle leases back."""
        st = self._classes[class_key]
        cap = cfg.max_tasks_in_flight_per_worker
        # dispatch — but never past the transport's backlog budget: a
        # connection already over rpc_send_backlog_limit_bytes stops
        # taking pushes until its drain completes (real flow control;
        # the dispatch path itself never awaits)
        limit = cfg.rpc_send_backlog_limit_bytes
        for lease in st.leases:
            while (
                st.queue and not lease.broken and lease.inflight < cap
                and lease.conn.send_backlog <= limit
            ):
                task = st.queue.popleft()
                lease.inflight += 1
                self._dispatch(class_key, lease, task, resources, strategy)
            if (
                st.queue and not lease.broken
                and lease.conn.send_backlog > limit
            ):
                self._drain_then_pump(class_key, lease, resources, strategy)
        if st.queue:
            # scale leases: one in-flight request per ~cap queued tasks
            # beyond current capacity — but never more than the pending-
            # request ceiling.  Unbounded want (= queue depth) let a deep
            # window park hundreds of lease requests at the GCS on a
            # saturated host, each costing a parked call's coroutine/
            # future/timer machinery (~12 allocs) for a grant that could
            # never arrive; grants re-pump, so a bounded pipeline loses
            # no ramp (reference: lease request pipelining,
            # direct_task_transport.cc).
            want = (len(st.queue) + cap - 1) // cap
            have = len(st.leases) + st.requests_inflight
            ceiling = cfg.sched_max_lease_requests_per_class
            if want > have and st.requests_inflight < ceiling:
                st.cancel_sent = False
                for _ in range(min(want - have, 8,
                                   ceiling - st.requests_inflight)):
                    st.requests_inflight += 1
                    self._loop.create_task(
                        self._acquire_lease(class_key, resources, strategy)
                    )
        else:
            # demand drained: cancel requests still parked at the GCS —
            # left alone, every freed slot would be granted to a parked
            # request, bounced back after the reuse grace, granted to the
            # next one, ... serially starving other classes/PGs for
            # grace × parked seconds (ray: CancelWorkerLease)
            if st.requests_inflight and not st.cancel_sent:
                st.cancel_sent = True
                self._spawn(
                    self.gcs.notify(
                        "cancel_lease_requests", {"tags": [st.tag]}
                    )
                )
            # idle leases (including ones granted after the queue drained)
            # go back to the GCS after a short reuse grace
            for lease in st.leases:
                if lease.inflight == 0 and not lease.broken:
                    self._schedule_lease_return(class_key, lease)

    async def _acquire_lease(self, class_key, resources, strategy):
        st = self._classes[class_key]
        pending_backoff = None  # built on first LEASE_PENDING only
        try:
            while True:
                try:
                    grant = await self.gcs.call(
                        "request_lease",
                        {
                            "resources": resources,
                            "strategy": strategy,
                            "tag": st.tag,
                            "runtime_env": self._class_runtime_envs.get(
                                class_key
                            ),
                        },
                        timeout=cfg.sched_max_pending_lease_s
                        + cfg.worker_start_timeout_s,
                    )
                    break
                except rpc.RemoteCallError as e:
                    # capacity-pending timeout at the GCS: keep waiting as
                    # long as we still have queued demand; infeasible → fail
                    if "LEASE_PENDING" in str(e.remote_exception) and st.queue:
                        # brief shared-policy backoff so a fleet of
                        # starved classes doesn't re-request in lockstep
                        if pending_backoff is None:
                            pending_backoff = lease_pending_backoff()
                        await pending_backoff.wait()
                        continue
                    raise
            if grant.get("cancelled"):
                # demand drained while parked — no lease; the pump below
                # re-requests if demand reappeared since the cancel
                pass
            else:
                try:
                    conn = await self._connect_worker(
                        grant["worker_addr"], grant.get("node_id")
                    )
                except (OSError, rpc.RpcError, asyncio.TimeoutError) as e:
                    # the granted worker died in the grant→dial window
                    # (crash, OOM kill, injected chaos).  Return the
                    # lease as broken and fall through to the pump —
                    # the still-queued demand re-requests.  (A bare
                    # return here stranded the queue forever: nothing
                    # re-pumped the class; found by the chaos plane's
                    # nth-hit lease-kill.)
                    logger.warning(
                        "granted worker at %s unreachable: %r",
                        grant["worker_addr"], e,
                    )
                    self._spawn(self.gcs.notify(
                        "return_lease",
                        {"lease_id": grant["lease_id"], "broken": True},
                    ))
                else:
                    lease = Lease(
                        lease_id=grant["lease_id"],
                        worker_addr=grant["worker_addr"],
                        worker_id=grant["worker_id"],
                        node_id=grant["node_id"],
                        conn=conn,
                    )
                    st.leases.append(lease)
        except Exception as e:
            # fail queued tasks if the demand is infeasible
            if st.queue and isinstance(e, rpc.RemoteCallError):
                for task in st.queue:
                    self._fail_task(task, TaskError(
                        "SchedulingError", str(e.remote_exception), "", "lease"
                    ))
                st.queue.clear()
            return
        finally:
            st.requests_inflight -= 1
        self._pump_class(class_key, resources, strategy)

    async def _connect_worker(self, addr: str,
                              node_hex: Optional[str] = None) -> rpc.Connection:
        conn = self._worker_conns.get(addr)
        if conn is None or conn.closed:
            conn = await rpc.connect(
                addr, self._worker_inbound, name=f"->worker@{addr}",
                on_close=self._on_worker_conn_closed,
                peer_endpoint=node_hex,
            )
            conn.peer_info["addr"] = addr
            self._worker_conns[addr] = conn
        elif node_hex is not None and conn.peer_endpoint is None:
            conn.peer_endpoint = node_hex
        return conn

    def _on_worker_conn_closed(self, conn) -> None:
        addr = conn.peer_info.get("addr")
        if addr is not None and self._worker_conns.get(addr) is conn:
            self._worker_conns.pop(addr, None)
        self._notify_peer_closed(conn)

    def _dispatch(self, class_key, lease: Lease, task: PendingTask,
                  resources, strategy):
        """Fire one task push and attach the reply callback — NO per-task
        coroutine/Task (the awaiting-coroutine shape cost a Task object +
        frame per call on the pipelined-task hot path; the actor path
        made the same move a round earlier)."""
        if self._consume_cancel_flag(task):  # cancelled in the pop→push window
            lease.inflight -= 1
            self._pump_class(class_key, resources, strategy)
            return
        task.rt = self
        task.st = lease
        task.conn = lease.conn
        self._inflight_dispatch[task.return_ids[0]] = task
        try:
            # call_soon: no wait_for timer / pending-pop bookkeeping per
            # task (same no-timeout semantics the old timeout=-1 had).
            # Its skipped write flow control is restored below: past the
            # backlog budget, spawn a drain so large pipelined arg
            # payloads hit the high-water mark instead of buffering
            # unbounded (pipelining is already capped per lease).
            if task.spec is not None:
                fut = lease.conn.call_soon("push_task", task.spec)
            else:
                # compact template wire: the skeleton ships once per
                # (connection, template); every later push is a 4-tuple.
                # The sent-set dies with the connection, so a worker that
                # never saw the skeleton (lost frame ⇒ lost conn) gets it
                # again on the replacement lease.
                tmpl = task.tmpl
                sent = lease.conn.peer_info.get("_tpl_sent")
                if sent is None:
                    sent = lease.conn.peer_info["_tpl_sent"] = set()
                if tmpl.tpl_id in sent:
                    payload = (tmpl.tpl_id, task.task_id, task.args,
                               task.job)
                else:
                    sent.add(tmpl.tpl_id)
                    payload = (tmpl.tpl_id, task.task_id, task.args,
                               task.job, tmpl.skeleton)
                fut = lease.conn.call_soon("push_task", payload)
        except (rpc.ConnectionLost, OSError):
            self._task_push_failed(task, lease,
                                   rpc.ConnectionLost("push failed"))
            self._dispatch_done(task, lease)
            return
        fut.add_done_callback(task.on_task_reply)
        if lease.conn.send_backlog > cfg.rpc_send_backlog_limit_bytes:
            # over budget after this push: pause dispatch onto this lease
            # (the pump skips draining/over-budget leases) and resume
            # pumping when the transport falls below the high-water mark
            self._drain_then_pump(
                task.class_key, lease, task.resources, task.strategy
            )

    def _drain_then_pump(self, class_key, lease: Lease, resources, strategy):
        """Await the lease connection's transport drain, then pump the
        class again.  One in-flight drain per lease; this is the awaiting
        fallback the call_soon contract requires (RT110)."""
        if lease.draining or lease.broken:
            return
        lease.draining = True

        async def _d():
            try:
                await lease.conn.drain()
            except (rpc.ConnectionLost, OSError):
                pass  # loss surfaces through the push reply futures
            finally:
                lease.draining = False
            self._pump_class(class_key, resources, strategy)

        self._loop.create_task(_d())

    def _on_task_push_reply(self, task: PendingTask, fut):
        lease = task.st
        try:
            if fut.cancelled():
                exc = rpc.ConnectionLost("push future cancelled")
            else:
                exc = fut.exception()
            if exc is None:
                reply = fut.result()
                try:
                    span = None
                    if type(reply) is tuple:
                        if len(reply) > 2:  # ("i", payload, t0, t1)
                            span = (reply[2], reply[3])
                    elif reply.get("exec_span"):
                        span = reply["exec_span"]
                    if span:
                        t0, t1 = span
                        self._record_exec(
                            task.name(), task.task_id.hex(),
                            lease.worker_id.hex()
                            if hasattr(lease.worker_id, "hex")
                            else str(lease.worker_id),
                            t0, t1 - t0,
                        )
                    self._apply_task_reply(task, reply)
                except Exception as e:  # noqa: BLE001
                    # the task RAN; a local failure applying its reply
                    # (e.g. result deserialization needs a worker-only
                    # module) must fail the ObjectRef, not re-queue the
                    # side effects and not leave the caller hanging on a
                    # never-resolved ref
                    self._fail_task(
                        task, TaskError.from_exception(
                            e, f"applying reply of {task.name()}"
                        )
                    )
            elif isinstance(exc, (rpc.ConnectionLost, rpc.RpcError, OSError)):
                # wire I/O failure ONLY reaches here before a reply is in
                # hand — break the lease and retry/fail (OSError covers
                # raw socket errors surfacing through the transport)
                self._task_push_failed(task, lease, exc)
            else:
                self._fail_task(task, TaskError(
                    "TaskDispatchError", repr(exc), "", task.name(),
                ))
        finally:
            self._dispatch_done(task, lease)

    def _task_push_failed(self, task: PendingTask, lease: Lease, exc):
        st = self._classes[task.class_key]
        lease.broken = True
        if task.retries_left > 0:
            task.retries_left -= 1
            st.queue.append(task)
        else:
            self._spawn(self._fail_task_worker_death(task, lease, exc))

    async def _fail_task_worker_death(self, task, lease, exc):
        # cold path: asking the GCS why the worker died needs an rpc
        detail = await self._worker_death_detail(lease.worker_id)
        self._fail_task(
            task,
            WorkerCrashedError(
                f"worker died while running {task.name()}: "
                f"{exc}{detail}"
            ),
        )

    def _dispatch_done(self, task: PendingTask, lease: Lease):
        class_key = task.class_key
        st = self._classes[class_key]
        self._inflight_dispatch.pop(task.return_ids[0], None)
        # the task may live on as a lineage record for as long as its
        # return refs do — drop the dispatch-time plumbing so a retained
        # record can't keep a dead Lease/Connection alive with it
        task.st = task.conn = None
        lease.inflight -= 1
        if lease.broken:
            if lease in st.leases:
                st.leases.remove(lease)
            self._spawn(
                self.gcs.notify(
                    "return_lease", {"lease_id": lease.lease_id, "broken": True}
                )
            )
        self._pump_class(class_key, task.resources, task.strategy)
        if not st.queue and lease.inflight == 0 and not lease.broken:
            self._schedule_lease_return(class_key, lease)

    def _schedule_lease_return(self, class_key, lease: Lease, grace: float = 0.25):
        def _return():
            st = self._classes.get(class_key)
            if st and lease in st.leases and lease.inflight == 0 and not st.queue:
                st.leases.remove(lease)
                self._spawn(
                    self.gcs.notify(
                        "return_lease", {"lease_id": lease.lease_id, "broken": False}
                    )
                )

        self._loop.call_later(grace, _return)

    def _apply_task_reply(self, task: PendingTask, reply: dict):
        if type(reply) is tuple:
            # compact single-inline-return shape ("i", payload) — the hot
            # actor-call reply (one tuple on the wire instead of
            # dict + returns list + item tuple)
            oid = task.return_ids[0]
            self._unhold_for_task(task.dep_oids)
            value = self._serialization.deserialize(reply[1])
            self.memory_store[oid] = value
            if oid in self._escaped and oid not in self._shared:
                try:
                    self.store.put(oid, reply[1], protect=True)
                    self._shared.add(oid)
                    self._gcs_object_notify(
                        "add_object_location",
                        {
                            "object_id": oid,
                            "node_id": bytes.fromhex(self.node_id),
                            "size": len(reply[1]),
                        },
                    )
                except ObjectExistsError:
                    self._shared.add(oid)
            self._cancel_requested.discard(oid)
            fut = self.result_futures.pop(oid, None)
            if (fut is not None and fut is not _PENDING_RESULT
                    and not fut.done()):
                fut.set_result(True)
            self._signal_sync_waiters(oid)
            self._maybe_release_after_reply(oid)
            return
        if reply["status"] == "error":
            self._fail_task(task, self._serialization.deserialize(reply["error"]))
            return
        if task.streaming:
            self._unhold_for_task(task.dep_oids)
            tid = task.task_id
            n = reply.get("streaming", 0)
            buf = self._streams.get(tid)
            consumed_upto = self._abandoned_streams.pop(tid, None)
            if buf is not None:
                buf.complete(n)
            elif consumed_upto is not None and n > consumed_upto:
                # consumer abandoned mid-stream: free the producer-stored
                # items it never took
                oids = [
                    ObjectID.for_task_return(TaskID(tid), i).binary()
                    for i in range(consumed_upto, n)
                ]
                self._gcs_object_notify("free_objects", {"object_ids": oids})
            return
        self._unhold_for_task(task.dep_oids)
        for oid, ret in zip(task.return_ids, reply["returns"]):
            kind = ret[0]
            if kind == "inline":
                value = self._serialization.deserialize(ret[1])
                self.memory_store[oid] = value
                if oid in self._escaped and oid not in self._shared:
                    # a borrower is waiting on the shared store: publish the
                    # raw serialized bytes there now — as a PROTECTED
                    # primary (an unprotected copy is LRU-evictable and the
                    # borrower's pull would find nothing)
                    try:
                        self.store.put(oid, ret[1], protect=True)
                        self._shared.add(oid)
                        self._gcs_object_notify(
                            "add_object_location",
                            {
                                "object_id": oid,
                                "node_id": bytes.fromhex(self.node_id),
                                "size": len(ret[1]),
                            },
                        )
                    except ObjectExistsError:
                        self._shared.add(oid)
            else:  # stored in shm on the producing node
                pass  # resolvable via store/pull path
            self._cancel_requested.discard(oid)
            fut = self.result_futures.pop(oid, None)
            if (fut is not None and fut is not _PENDING_RESULT
                    and not fut.done()):
                fut.set_result(True)
            self._signal_sync_waiters(oid)
            self._maybe_release_after_reply(oid)

    def _fail_task(self, task: PendingTask, exc: Exception):
        self._unhold_for_task(task.dep_oids)
        if task.streaming:
            # already-delivered items stay readable; the consumer's next()
            # raises.  Never write _RaiseOnGet into return oids here — item
            # 0 shares its oid with return id 0 and may hold a real value.
            tid = task.task_id
            self._abandoned_streams.pop(tid, None)
            buf = self._streams.get(tid)
            if buf is not None:
                buf.fail(exc)
            return
        for oid in task.return_ids:
            self._cancel_requested.discard(oid)
            self.memory_store[oid] = _RaiseOnGet(exc)
            fut = self.result_futures.pop(oid, None)
            if (fut is not None and fut is not _PENDING_RESULT
                    and not fut.done()):
                fut.set_result(True)
            self._signal_sync_waiters(oid)
            self._maybe_release_after_reply(oid)

    # ---- actors (client side) ------------------------------------------
    def create_actor(
        self,
        cls,
        args,
        kwargs,
        *,
        name=None,
        namespace="default",
        get_if_exists=False,
        num_returns=1,
        resources=None,
        max_restarts=0,
        max_task_retries=0,
        detached=False,
        strategy=None,
        runtime_env=None,
        max_concurrency=None,
        concurrency_groups=None,
        method_groups=None,
        on_drain="migrate",
    ) -> "ActorID":
        actor_id = ActorID.random()
        rtenv_desc = self._normalize_runtime_env(runtime_env)
        cls_hash = self.fn_hash_and_register(cls)
        creation_spec = {
            "cls_hash": cls_hash,
            "args": self._pack_args(args, kwargs),
            "max_task_retries": max_task_retries,
            "job": self._job_hex(),
        }
        if max_concurrency is not None:
            creation_spec["max_concurrency"] = int(max_concurrency)
        if concurrency_groups:
            # named groups with per-group limits (reference:
            # python/ray/actor.py:521-539 concurrency_groups)
            creation_spec["concurrency_groups"] = {
                str(k): int(v) for k, v in concurrency_groups.items()
            }
            creation_spec["method_groups"] = dict(method_groups or {})
        resources = dict(resources if resources is not None else {"CPU": 1})
        reply = self._run(
            self.gcs.call(
                "register_actor",
                {
                    "actor_id": actor_id.binary(),
                    "job_id": self.job_id.binary() if self.job_id else None,
                    "name": name,
                    "namespace": namespace,
                    "get_if_exists": get_if_exists,
                    "max_restarts": max_restarts,
                    "creation_spec": creation_spec,
                    "resources": resources,
                    "strategy": strategy or {},
                    "detached": detached,
                    "runtime_env": rtenv_desc,
                    "on_drain": on_drain,
                },
            )
        )
        if reply.get("existing"):
            return ActorID(reply["actor_id"])
        self._spawn(self._create_actor_async(actor_id, creation_spec, resources,
                                             strategy or {}, rtenv_desc))
        return actor_id

    async def _create_actor_async(self, actor_id, creation_spec, resources,
                                  strategy, runtime_env=None):
        pending_backoff = None  # built on first LEASE_PENDING only
        try:
            while True:
                try:
                    grant = await self.gcs.call(
                        "request_lease",
                        {
                            "resources": resources,
                            "strategy": strategy,
                            "actor_id": actor_id.binary(),
                            "runtime_env": runtime_env,
                        },
                        timeout=cfg.sched_max_pending_lease_s
                        + cfg.worker_start_timeout_s,
                    )
                    break
                except rpc.RemoteCallError as e:
                    # capacity-pending: keep waiting — an actor whose demand
                    # is feasible must eventually place (infeasible demands
                    # error immediately at the GCS instead)
                    if "LEASE_PENDING" in str(e.remote_exception):
                        if pending_backoff is None:
                            pending_backoff = lease_pending_backoff()
                        await pending_backoff.wait()
                        continue
                    raise
            conn = await self._connect_worker(
                grant["worker_addr"], grant.get("node_id")
            )
            # No wall-clock deadline on __init__: arbitrarily long startup
            # (jax import, backend init, first compile) is legal as long as
            # the worker process is alive — its death breaks this TCP
            # connection, which is the liveness signal (the reference's
            # analogue: actor creation has no fixed timeout either; failure
            # is detected via worker death, gcs_actor_manager.cc).
            await conn.call(
                "create_actor",
                {
                    "actor_id": actor_id.binary(),
                    "creation_spec": creation_spec,
                },
                timeout=-1,
            )
            await self.gcs.call(
                "actor_started",
                {
                    "actor_id": actor_id.binary(),
                    "worker_addr": grant["worker_addr"],
                    "node_id": grant["node_id"],
                    "lease_id": grant["lease_id"],
                },
            )
            self._actor_addrs[actor_id.binary()] = grant["worker_addr"]
        except Exception as e:
            logger.warning("actor creation failed: %r", e)
            try:
                await self.gcs.call(
                    "actor_creation_failed",
                    {"actor_id": actor_id.binary(), "reason": repr(e)},
                )
            except Exception:
                pass

    async def _actor_conn(self, actor_id: bytes):
        """Connection to the actor's worker, waiting through PENDING/RESTARTING.

        Liveness-based, not deadline-based: an actor may spend minutes in
        __init__ (jax backend init + first XLA compile routinely exceed any
        fixed budget).  The GCS is the liveness authority — worker/node death
        transitions the actor to DEAD (or RESTARTING → replay), so waiting on
        a non-DEAD state can only block while the creation is genuinely in
        progress."""
        conn = self._actor_conns.get(actor_id)
        if conn is not None and not conn.closed:
            return conn
        # stale-address redials + state polls ride the shared backoff
        # policy (liveness-based wait: no deadline, the GCS's DEAD
        # transition is the exit)
        retry_backoff = Backoff(BackoffPolicy(
            base_s=cfg.backoff_base_s, mult=cfg.backoff_mult,
            max_s=1.0, jitter_frac=cfg.backoff_jitter_frac,
        ))
        while True:
            info = await self.gcs.call(
                "get_actor", {"actor_id": actor_id, "wait": 5.0}, timeout=-1
            )
            if info is None:
                raise ActorDiedError(f"actor {actor_id.hex()[:12]} unknown")
            if info["state"] == "ALIVE" and info["worker_addr"]:
                try:
                    conn = await rpc.connect(
                        info["worker_addr"], self._worker_inbound,
                        name="->actor",
                        # label for the partition plane: the actor's
                        # hosting node is its network identity
                        peer_endpoint=info.get("node_id"),
                    )
                    self._actor_conns[actor_id] = conn
                    self._actor_addrs[actor_id] = info["worker_addr"]
                    return conn
                except OSError:
                    pass  # stale address; retry
            elif info["state"] == "DEAD":
                raise ActorDiedError(
                    f"actor {actor_id.hex()[:12]} is dead: {info.get('death_cause')}"
                )
            await retry_backoff.wait()

    def make_actor_skeleton(
        self,
        actor_id: ActorID,
        method_name: str,
        num_returns=1,
        concurrency_group: Optional[str] = None,
    ) -> tuple:
        """(spec skeleton, fill_job) for one actor method / option-set —
        the actor twin of make_task_template, cached by ActorMethod."""
        skeleton = {
            "task_id": b"",  # filled per call
            "actor_id": actor_id.binary(),
            "method": method_name,
            "args": (),      # filled per call
            "num_returns": 1 if num_returns == "streaming" else num_returns,
            "caller_id": self.worker_id.binary(),
            # seq/seq_epoch are assigned at push time by the actor pump
        }
        if num_returns == "streaming":
            skeleton["streaming"] = True
        if concurrency_group:
            skeleton["concurrency_group"] = concurrency_group
        fill_job = self.job_id is None
        if not fill_job:
            skeleton["job"] = self._job_hex()
        return skeleton, fill_job

    def submit_actor_task_from_skeleton(
        self, skeleton: dict, fill_job: bool, args, kwargs, retries: int = 0
    ):
        """Hot-path actor submit.  Returns a bare ObjectRef for a single
        return, a list otherwise, an ObjectRefGenerator when streaming."""
        aid = skeleton["actor_id"]
        task_id = os.urandom(16)
        sub_idx = self._actor_seq.get(aid, 0)
        self._actor_seq[aid] = sub_idx + 1
        streaming = "streaming" in skeleton
        if streaming:
            retries = 0  # re-running a generator would double-send items
        spec = dict(skeleton)
        spec["task_id"] = task_id
        spec["args"] = self._pack_args(args, kwargs)
        if fill_job:
            spec["job"] = self._job_hex()
        if tracing.enabled():
            with tracing.span(
                f"submit {spec['method']}", task_id=task_id.hex(),
                actor_id=aid.hex(),
            ):
                spec["trace_ctx"] = tracing.inject()
        n = spec["num_returns"]
        if n == 1:
            return_ids = (task_return_binary(task_id, 0),)
        else:
            return_ids = tuple(
                task_return_binary(task_id, i) for i in range(n)
            )
        dep_oids = () if not spec["args"] else [
            item[1] if item[0] == "ref" else item[2]
            for item in spec["args"]
            if item[0] in ("ref", "kwref")
        ]
        task = PendingTask(
            spec, return_ids, retries, sub_idx=sub_idx, dep_oids=dep_oids,
            task_id=task_id, streaming=streaming,
        )
        if dep_oids:
            self._hold_for_task(dep_oids)
        if streaming:
            self._streams[task_id] = _StreamBuf()
            self._submit_to_loop(task)
            return ObjectRefGenerator(task_id)
        for oid in return_ids:
            self.result_futures[oid] = _PENDING_RESULT
        if n == 1:
            ref = ObjectRef(ObjectID(return_ids[0]))
            self._submit_to_loop(task)
            return ref
        refs = [ObjectRef(ObjectID(oid)) for oid in return_ids]
        self._submit_to_loop(task)
        return refs

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args,
        kwargs,
        num_returns: int = 1,
        retries: int = 0,
        concurrency_group: Optional[str] = None,
    ):
        """Untemplated actor submit (compatibility surface); returns a
        list of refs (or a generator) like it always did."""
        skeleton, fill_job = self.make_actor_skeleton(
            actor_id, method_name, num_returns, concurrency_group
        )
        out = self.submit_actor_task_from_skeleton(
            skeleton, fill_job, args, kwargs, retries
        )
        if isinstance(out, ObjectRef):
            return [out]
        return out

    def _enqueue_actor_task(self, task: PendingTask):
        aid = task.spec["actor_id"]
        st = self._actor_states.get(aid)
        if st is None:
            st = self._actor_states[aid] = ActorClientState(
                queue=deque(), wake=asyncio.Event()
            )
        # Fast path (the hot loop for steady traffic): connection is
        # live and nothing is queued ahead — assign the wire seq inline
        # and push directly, skipping the pump wake hop.  Safe because
        # this runs on the io loop (serial with the pump's drain, which
        # never awaits mid-drain), so submission order == wire order is
        # preserved; the task lands in st.inflight like any other, so
        # the pump's reconnect replay still covers it.
        if (
            st.pump_running
            and not st.dead
            and st.conn is not None
            and not st.conn.closed
            and not st.queue
            # a stalled peer's write buffer must push new calls onto the
            # queue so the PUMP (which awaits drain) provides the flow
            # control call_soon skips
            and st.conn.send_backlog < cfg.rpc_send_backlog_limit_bytes
        ):
            if not self._consume_cancel_flag(task):
                task.spec["seq"] = st.wire_seq
                task.spec["seq_epoch"] = st.epoch
                st.wire_seq += 1
                st.inflight[task.sub_idx] = task
                self._dispatch_actor_push(aid, st, st.conn, task)
            return
        st.queue.append(task)
        st.wake.set()
        if not st.pump_running:
            st.pump_running = True
            self._loop.create_task(self._actor_pump(aid, st))

    async def _actor_pump(self, aid: bytes, st: ActorClientState):
        """Single pusher per actor: establishes the connection, assigns
        wire (epoch, seq) pairs in submission order, and re-pushes unacked
        calls — still in submission order — after a connection loss."""
        while True:
            while st.queue or st.inflight:
                if st.conn is None or st.conn.closed:
                    # requeue unacked calls ahead of fresh ones, in order
                    if st.inflight:
                        requeue = []
                        for k in sorted(st.inflight):
                            t = st.inflight.pop(k)
                            if t.retries_left == 0:
                                self._fail_task(
                                    t,
                                    ActorDiedError(
                                        f"actor {aid.hex()[:12]} died while "
                                        f"running {t.spec['method']}"
                                    ),
                                )
                                continue
                            if t.retries_left > 0:
                                t.retries_left -= 1
                            requeue.append(t)
                        st.queue.extendleft(reversed(requeue))
                    if not st.queue and not st.inflight:
                        break
                    self._actor_conns.pop(aid, None)
                    try:
                        st.conn = await self._actor_conn(aid)
                    except ActorDiedError as e:
                        for t in list(st.queue):
                            self._fail_task(t, e)
                        st.queue.clear()
                        st.dead = True
                        break
                    st.epoch += 1
                    st.wire_seq = 0
                while st.queue:
                    t = st.queue.popleft()
                    if self._consume_cancel_flag(t):
                        continue
                    t.spec["seq"] = st.wire_seq
                    t.spec["seq_epoch"] = st.epoch
                    st.wire_seq += 1
                    st.inflight[t.sub_idx] = t
                    self._dispatch_actor_push(aid, st, st.conn, t)
                    if (
                        st.conn is not None
                        and st.conn.send_backlog
                        > cfg.rpc_send_backlog_limit_bytes
                    ):
                        # flow control: call_soon skipped drain(), so the
                        # pump awaits it — a stalled actor must apply
                        # backpressure to submitters, not buffer every
                        # serialized call in the transport until OOM
                        try:
                            await st.conn.drain()
                        except (rpc.ConnectionLost, OSError):
                            break  # loss path re-queues via st.inflight
                st.wake.clear()
                if st.inflight:
                    # woken by new submissions, a connection break, or the
                    # last in-flight reply landing (so the pump can exit)
                    st.draining = True
                    try:
                        await st.wake.wait()
                    finally:
                        st.draining = False
            if st.dead:
                st.pump_running = False
                return
            # idle: stay RESIDENT, parked on the wake event — exiting
            # here made every serial caller pay a pump restart per call.
            # Park with a timeout so pumps of killed/idle actors retire
            # instead of leaking a task per dead actor forever (nothing
            # wakes an idle pump when its actor is killed).
            st.wake.clear()
            # re-check BOTH queue and inflight: an eager fast-path submit
            # places the task straight into st.inflight, so a pump that
            # retires on an empty queue alone would orphan it — a later
            # connection loss then has no pump to re-push it.
            if not st.queue and not st.inflight:
                try:
                    await asyncio.wait_for(st.wake.wait(), timeout=60.0)
                except asyncio.TimeoutError:
                    if not st.queue and not st.inflight:
                        st.pump_running = False
                        return

    def _dispatch_actor_push(
        self, aid: bytes, st: ActorClientState, conn, task: PendingTask
    ):
        """Fire the push and attach the reply callback — NO per-call
        coroutine/Task (the old awaiting-coroutine shape cost a Task
        object + frame per call on the submission hot path)."""
        task.rt = self
        task.st = st
        task.conn = conn
        # the task itself is the dispatch registry entry (task_id + conn
        # ride its slots) — no per-call tuple
        self._inflight_dispatch[task.return_ids[0]] = task
        try:
            # RT110 audited + baselined: backlog policing lives in the
            # CALLERS — the pump awaits drain() past the budget after
            # each push, and the _enqueue_actor_task fast path only
            # dispatches while send_backlog is under budget
            fut = conn.call_soon("push_actor_task", task.spec)
        except (rpc.ConnectionLost, OSError):
            # Leave the task in st.inflight; the pump reconnects and
            # re-pushes.  Only signal if WE carry the current connection.
            # Clean the dispatch entry (the callback path's finally does
            # this) — a stale entry would make cancel() target a dead
            # conn instead of flagging the re-push for drop-on-arrival.
            cur = self._inflight_dispatch.get(task.return_ids[0])
            if cur is not None and cur.conn is conn:
                self._inflight_dispatch.pop(task.return_ids[0], None)
            if st.conn is conn:
                st.conn = None
                st.wake.set()
            return
        # bound method, not a closure: rt/st/conn ride the task's slots,
        # so the reply callback costs one object instead of fn + cells
        fut.add_done_callback(task.on_push_reply)

    def _on_push_reply(
        self, st: ActorClientState, conn, task: PendingTask, fut
    ):
        try:
            exc = None if fut.cancelled() else fut.exception()
            if fut.cancelled():
                exc = rpc.ConnectionLost("push future cancelled")
            if exc is None:
                st.inflight.pop(task.sub_idx, None)
                if not st.inflight and st.draining:
                    # wake ONLY a pump parked mid-drain on this event;
                    # waking the idle 60s park costs a task resume +
                    # fresh timer per call, which dominated the serial
                    # sync-call path
                    st.wake.set()
                self._apply_task_reply(task, fut.result())
            elif isinstance(exc, (rpc.ConnectionLost, OSError)):
                # ConnectionLost subclasses RpcError: checked FIRST.
                # Leave the task in st.inflight; the pump reconnects and
                # re-pushes.  Only signal if WE carry the current
                # connection — a stale callback observing an old conn's
                # loss after the pump already reconnected must not
                # clobber the fresh one.
                if st.conn is conn:
                    st.conn = None
                    st.wake.set()
            elif isinstance(exc, rpc.RpcError):
                st.inflight.pop(task.sub_idx, None)
                if not st.inflight and st.draining:
                    st.wake.set()
                self._fail_task(task, TaskError(
                    "ActorCallError", str(exc), "", task.spec["method"]
                ))
            else:
                st.inflight.pop(task.sub_idx, None)
                if not st.inflight and st.draining:
                    st.wake.set()
                self._fail_task(task, TaskError(
                    "ActorCallError", repr(exc), "", task.spec["method"]
                ))
        finally:
            cur = self._inflight_dispatch.get(task.return_ids[0])
            if cur is not None and cur.conn is conn:
                self._inflight_dispatch.pop(task.return_ids[0], None)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._run(
            self.gcs.call(
                "kill_actor",
                {"actor_id": actor_id.binary(), "no_restart": no_restart},
            )
        )

    # ---- misc ----------------------------------------------------------
    def cancel(self, ref: ObjectRef) -> bool:
        """Cancel the task producing ``ref``.

        Queued client-side → removed before dispatch.  Already running →
        a ``cancel_task`` RPC interrupts the executing thread on the worker
        (reference: CoreWorker::CancelTask → HandleCancelTask raising
        TaskCancelledError in the Cython execution wrapper; interruption is
        best-effort at bytecode boundaries, like the reference)."""
        return self._run(self._cancel_async(ref.object_id.binary()))

    async def _cancel_async(self, oid: bytes) -> bool:
        # On the io loop: serialized with enqueue/dispatch, no scan races.
        for st in self._classes.values():
            for task in list(st.queue):
                if oid in task.return_ids:
                    st.queue.remove(task)
                    self._fail_task(task, TaskCancelledError(oid.hex()))
                    return True
        for ast in self._actor_states.values():
            for task in list(ast.queue):
                if oid in task.return_ids:
                    ast.queue.remove(task)
                    self._fail_task(task, TaskCancelledError(oid.hex()))
                    return True
        entry = self._inflight_dispatch.get(oid)
        if entry is not None and entry.conn is not None:
            self._spawn(
                entry.conn.call("cancel_task", {"task_id": entry.task_id})
            )
            return True
        if oid in self.result_futures:
            # submitted but not yet enqueued (waiting on local deps):
            # flag it; _enqueue_task drops it on arrival
            self._cancel_requested.add(oid)
            return True
        return False

    def free(self, refs: List[ObjectRef]):
        oids = [r.object_id.binary() for r in refs]
        for oid in oids:
            self.memory_store.pop(oid, None)
            self._shared.discard(oid)
        # windowed location announces must reach the GCS before the free
        # (a free seen first plants a tombstone and the late announce is
        # dropped — the stored primary would never be deleted)
        self.flush_object_notifies()
        self._run(self.gcs.call("free_objects", {"object_ids": oids}))

    # ---- distributed refcounting ---------------------------------------
    def on_ref_created(self, object_id: ObjectID):
        oid = object_id.binary()
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) + 1
            self._local_refs[oid] = n
            if n == 1:
                if oid in self._pending_ref_del:
                    # re-created before the release flushed: net effect is
                    # "still held" — cancel the pending del
                    self._pending_ref_del.discard(oid)
                    self._ref_registered.add(oid)
                elif oid not in self._ref_registered:
                    self._ref_registered.add(oid)
                    self._pending_ref_add.add(oid)
                    self._schedule_ref_flush()

    def on_ref_deleted(self, object_id: ObjectID):
        oid = object_id.binary()
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
                return
            self._local_refs.pop(oid, None)
            if self._task_holds.get(oid, 0) > 0:
                return  # still pinned as an in-flight task dependency
        self._release_local(oid)

    def _hold_for_task(self, oids):
        with self._ref_lock:
            for oid in oids:
                self._task_holds[oid] = self._task_holds.get(oid, 0) + 1

    def _unhold_for_task(self, oids):
        released = []
        with self._ref_lock:
            for oid in oids:
                n = self._task_holds.get(oid, 0) - 1
                if n > 0:
                    self._task_holds[oid] = n
                else:
                    self._task_holds.pop(oid, None)
                    if self._local_refs.get(oid, 0) == 0:
                        released.append(oid)
        for oid in released:
            self._release_local(oid)

    def _release_local(self, oid: bytes):
        """Last local reference (and task hold) is gone: drop the local
        value and tell the GCS this process no longer holds the object."""
        if self._closed:
            return
        was_shared = oid in self._shared
        self.memory_store.pop(oid, None)
        self._shared.discard(oid)
        self._escaped.discard(oid)
        self._release_lineage_return(oid)
        with self._ref_lock:
            self._deferred_reg.discard(oid)
            if oid in self._ref_registered:
                self._ref_registered.discard(oid)
                if (
                    oid in self._pending_ref_add
                    and not was_shared
                    and oid not in self.result_futures
                ):
                    # the add never went out and nothing cluster-side
                    # exists (local-only value, no in-flight outcome):
                    # cancel the pair outright instead of planting a
                    # holder entry the GCS would never delete
                    self._pending_ref_add.discard(oid)
                else:
                    # the del is sent in the same or a later window as its
                    # add (adds flush before dels; an add parked for an
                    # in-flight result HOLDS its del — see
                    # _flush_ref_events): the GCS must see the holder set
                    # empty to free any stored copies
                    self._pending_ref_del.add(oid)
                    self._schedule_ref_flush()

    def _schedule_ref_flush(self):
        # caller holds _ref_lock (every call site takes it; the flush
        # callback clears the flag under it too) — locked, just not
        # lexically here, which is past what rtrace can see
        if self._ref_flush_scheduled or self._closed:
            return
        # rtlint: disable-next=RT301
        self._ref_flush_scheduled = True
        try:
            self._loop.call_soon_threadsafe(
                self._loop.call_later, cfg.ref_flush_interval_s,
                self._flush_ref_events,
            )
        except RuntimeError:
            # loop closing; same caller-held _ref_lock as the set above
            # rtlint: disable-next=RT301
            self._ref_flush_scheduled = False

    def _flush_ref_events(self):
        with self._ref_lock:
            add = []
            revisit = []
            for oid in self._pending_ref_add:
                if (
                    oid in self.memory_store
                    and oid not in self._shared
                    and oid not in self._escaped
                ):
                    # LOCAL-ONLY inline result: its value lives solely in
                    # this process's memory store and no other process can
                    # reach the ref (escape requires serialization, which
                    # promotes via ensure_shared first) — cluster-wide
                    # holder tracking would be 2 GCS messages + free
                    # scheduling per task for nothing (the dominant
                    # per-task GCS cost for small-result task storms).
                    # ensure_shared re-registers on a later escape.
                    self._ref_registered.discard(oid)
                    self._deferred_reg.add(oid)
                elif oid in self.result_futures and oid not in self._escaped:
                    # OUR in-flight task return: nothing exists cluster-
                    # side yet, so a holder add is premature — re-check
                    # next flush window once the reply landed (then it
                    # either defers as inline-local or registers as
                    # stored).  Safe against the GCS free machinery:
                    # frees are only scheduled on holder-set DELETIONS,
                    # never on first registration of locations.
                    revisit.append(oid)
                else:
                    add.append(oid)
            # a del whose add is still parked must WAIT for it (an
            # unpaired del is a GCS no-op and the later add would plant a
            # holder entry nothing deletes — the fire-and-forget leak)
            revisit_set = set(revisit)
            dels = [
                oid for oid in self._pending_ref_del
                if oid not in revisit_set
            ]
            held_dels = [
                oid for oid in self._pending_ref_del if oid in revisit_set
            ]
            self._pending_ref_add.clear()
            self._pending_ref_add.update(revisit)
            self._pending_ref_del.clear()
            self._pending_ref_del.update(held_dels)
            self._ref_flush_scheduled = False
            if revisit:
                self._schedule_ref_flush()
        if (add or dels) and self.gcs and not self.gcs.closed:
            # rides the object-notify coalescer: a ref window that
            # coincides with pending location announces shares their rpc
            self._gcs_object_notify(
                "ref_update",
                {
                    "holder": self.worker_id.binary(),
                    "add": add,
                    "del": dels,
                },
            )

    def _maybe_release_after_reply(self, oid: bytes):
        """A task reply landed a value for ``oid`` but every ref died while
        the task ran — release immediately so unobserved results can't
        accumulate in the memory store."""
        with self._ref_lock:
            live = self._local_refs.get(oid, 0) > 0 or self._task_holds.get(
                oid, 0
            ) > 0
        if not live:
            self._release_local(oid)

    # ---- lineage + reconstruction --------------------------------------
    def _record_lineage(self, task: PendingTask):
        budget = cfg.lineage_reconstruction_max
        if budget <= 0:
            return
        # the PendingTask IS the lineage record (slotted store): liveness
        # is a bitmask over return_ids positions, the budget an int slot —
        # zero container allocations per recorded task
        task.lineage_budget = budget
        task.live_mask = (1 << len(task.return_ids)) - 1
        self._lineage.insert(task)
        by_ret = self._lineage_by_return
        for oid in task.return_ids:
            by_ret[oid] = task

    def _release_lineage_return(self, oid: bytes):
        rec = self._lineage_by_return.pop(oid, None)
        if rec is None:
            return
        rids = rec.return_ids
        if len(rids) == 1:
            rec.live_mask = 0
        else:
            try:
                rec.live_mask &= ~(1 << rids.index(oid))
            except ValueError:
                pass
        if rec.live_mask == 0:
            self._lineage.remove(rec.task_id)

    async def _try_reconstruct(self, oid: bytes) -> bool:
        """Re-execute the task that produced ``oid`` (lineage recovery).

        Returns True if a reconstruction is running (caller loops back to
        waiting on the result future).  Runs on the io loop."""
        rec = self._lineage_by_return.get(oid)
        if rec is None:
            return False
        if rec.recon_inflight or oid in self.result_futures:
            return True  # already being reconstructed
        if rec.lineage_budget <= 0:
            return False
        rec.lineage_budget -= 1
        rec.recon_inflight = True
        self.reconstructions += 1
        try:
            logger.info(
                "reconstructing object %s via task %s (budget left %d)",
                oid.hex()[:12], rec.task_id.hex()[:12], rec.lineage_budget,
            )
            # Recover dependencies first: resolving them triggers their own
            # reconstruction recursively through this same path, then
            # re-promote each to the shared store for the executing worker.
            for dep in rec.dep_oids:
                value = await self._resolve_one(dep, None)
                if not self.store.contains(dep):
                    self._shared.discard(dep)
                    self._write_to_store(
                        dep, self._serialization.serialize(value)
                    )
            # fresh dispatchable task sharing the record's immutable state
            # (the record itself stays in the slot tracking budget/liveness)
            task = PendingTask(
                rec.spec, rec.return_ids,
                retries_left=0,
                class_key=rec.class_key,
                resources=rec.resources,
                strategy=rec.strategy,
                tmpl=rec.tmpl, task_id=rec.task_id, args=rec.args,
                job=rec.job, streaming=rec.streaming,
            )
            for roid in rec.return_ids:
                if roid not in self.result_futures:
                    self.memory_store.pop(roid, None)
                    self.result_futures[roid] = _PENDING_RESULT
            self._enqueue_task(task)
            return True
        finally:
            rec.recon_inflight = False

    def cluster_resources(self) -> dict:
        return self._run(self.gcs.call("cluster_resources", {}))

    def nodes(self) -> list:
        return self._run(self.gcs.call("get_nodes", {}))


class _StreamBuf:
    """Caller-side buffer of one streaming task's delivered item indexes.

    The io loop delivers (`deliver`, `complete`, `fail`); the consumer
    thread waits in `Runtime.stream_next` on `cond`.  Item values live in
    the runtime memory store / shm keyed by for_task_return(tid, idx) —
    this tracks only arrival and ordering."""

    __slots__ = (
        "cond", "items", "next_idx", "count", "failed", "conn",
        "cancel_state", "aev",
    )

    def __init__(self):
        self.cond = threading.Condition()
        self.items: set = set()   # delivered, not yet consumed indexes
        self.next_idx = 0
        self.count: Optional[int] = None  # total items once producer done
        self.failed: Optional[Exception] = None
        self.conn = None  # connection items arrived on (for acks/cancel)
        self.cancel_state = 0  # 0 none, 1 requested (conn unknown), 2 sent
        # loop-native waiter (stream_next_async); all signal paths run ON
        # the io loop, so setting an asyncio.Event here is safe
        self.aev: Optional[Any] = None

    def _signal(self):
        self.cond.notify_all()
        if self.aev is not None:
            self.aev.set()

    def deliver(self, idx: int, conn):
        with self.cond:
            self.items.add(idx)
            self.conn = conn
            self._signal()

    def complete(self, count: int):
        with self.cond:
            self.count = count
            self._signal()

    def fail(self, exc: Exception):
        with self.cond:
            self.failed = exc
            self._signal()


class ObjectRefGenerator:
    """Iterator over a streaming task's return refs (reference:
    ObjectRefGenerator, python/ray/_raylet.pyx:273).  Each next() blocks
    until the producer has yielded the next item and returns an ObjectRef
    resolvable with ray_tpu.get; a mid-stream producer error arrives as a
    ref whose get raises, after which the stream ends."""

    def __init__(self, task_id: bytes):
        self._task_id = task_id
        self._exhausted = False

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        if self._exhausted:
            raise StopIteration
        try:
            return get_runtime().stream_next(self._task_id)
        except StopIteration:
            self._exhausted = True
            raise

    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        if self._exhausted:
            raise StopAsyncIteration
        try:
            return await get_runtime().stream_next_async(self._task_id)
        except (StopIteration, StopAsyncIteration):
            self._exhausted = True
            raise StopAsyncIteration from None

    def next_with_timeout(self, timeout: float) -> "ObjectRef":
        return get_runtime().stream_next(self._task_id, timeout=timeout)

    @property
    def task_id(self) -> bytes:
        return self._task_id

    def __del__(self):
        if not self._exhausted:
            try:
                get_runtime().stream_abandon(self._task_id)
            except Exception:
                pass

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()[:16]})"


# get()-fast-path sentinel: "this ref needs the full async resolve path"
_SYNC_MISS = object()


class _RaiseOnGet:
    """Sentinel stored in the memory store for errored returns."""

    __slots__ = ("exc",)

    def __init__(self, exc: Exception):
        self.exc = exc


def _delayed_exit():
    time.sleep(0.1)
    os._exit(0)
