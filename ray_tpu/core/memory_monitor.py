"""Node memory monitor + OOM worker-killing policy.

Role-equivalent of ray: src/ray/common/memory_monitor.h:52 (usage
polling against a threshold) and raylet/worker_killing_policy*.cc (pick
a victim instead of letting the kernel OOM-killer take the raylet or
the GCS).  Runs as an asyncio task inside the raylet.

Usage is the max of system pressure (1 - MemAvailable/MemTotal from
/proc/meminfo) and cgroup-v2 pressure (memory.current/memory.max) so
containerized nodes respect their limit, not the host's.

Victim policy (reference: retriable-FIFO + group-by-owner, collapsed):
prefer the most recently leased busy worker — its task has the least
progress to lose and the core's existing worker-crash machinery retries
it; idle pooled workers are killed first since that fails nothing.
A killed worker surfaces to the driver as WorkerCrashedError with an
OOM hint in the reason, mirroring the reference's OomKiller message.

For tests (and only tests): `RT_MEMORY_MONITOR_FAKE_USAGE_FILE` points
at a file holding a float usage fraction that overrides measurement —
the same trick the reference plays with its fake memory monitor.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from ray_tpu.common.config import cfg

logger = logging.getLogger(__name__)


def measure_usage_fraction() -> float:
    """Max of host and cgroup-v2 memory pressure, in [0, 1]."""
    fake = cfg.memory_monitor_fake_usage_file
    if fake:
        try:
            with open(fake) as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return 0.0
    frac = 0.0
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                info[k] = int(rest.strip().split()[0])
        total = info.get("MemTotal", 0)
        avail = info.get("MemAvailable", 0)
        if total > 0:
            frac = 1.0 - avail / total
    except OSError:
        pass
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw != "max":
            limit = int(raw)
            with open("/sys/fs/cgroup/memory.current") as f:
                cur = int(f.read().strip())
            if limit > 0:
                frac = max(frac, cur / limit)
    except (OSError, ValueError):
        pass
    return frac


class MemoryMonitor:
    def __init__(self, raylet):
        self.raylet = raylet
        self.kills = 0
        self._last_kill = 0.0

    def pick_victim(self):
        """Idle pooled workers first; else the most recently LEASED
        worker (leased_at, not spawn time — pooled workers are reused,
        so spawn order says nothing about task progress)."""
        workers = [
            w for w in self.raylet.workers.values()
            if w.proc.poll() is None
        ]
        idle = [w for w in workers if w.idle]
        if idle:
            return max(idle, key=lambda w: w.started_at), "idle"
        busy = [w for w in workers if w.lease_id is not None]
        if busy:
            return max(busy, key=lambda w: w.leased_at), "busy"
        return None, ""

    async def step(self) -> Optional[str]:
        """One poll; returns the killed worker id hex (or None)."""
        usage = measure_usage_fraction()
        if usage < cfg.memory_usage_threshold:
            return None
        # one kill per grace window: give freed memory time to register
        now = time.monotonic()
        if now - self._last_kill < cfg.memory_monitor_kill_grace_s:
            return None
        victim, kind = self.pick_victim()
        if victim is None:
            return None
        self._last_kill = now
        self.kills += 1
        logger.warning(
            "memory monitor: usage %.3f >= %.3f, killing %s worker %s",
            usage, cfg.memory_usage_threshold, kind,
            victim.worker_id.hex()[:12],
        )
        try:
            victim.proc.kill()
        except Exception:
            pass
        await self.raylet._on_worker_exit(
            victim,
            reason=(
                f"worker killed by the node memory monitor (node memory "
                f"usage {usage:.2f} >= threshold "
                f"{cfg.memory_usage_threshold:.2f}); task will be retried "
                "if retriable"
            ),
        )
        return victim.worker_id.hex()

    async def loop(self):
        while True:
            await asyncio.sleep(cfg.memory_monitor_interval_s)
            try:
                await self.step()
            except Exception:
                logger.exception("memory monitor step failed")
