"""Node bring-up: spawn GCS and raylet processes for a head or worker node.

Role-equivalent of ray: python/ray/_private/node.py:37 and services.py
(start_gcs_server:1432, start_raylet:1496).
"""

from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.common.ids import NodeID


def _read_tagged_line(proc: subprocess.Popen, tag: str, timeout: float) -> str:
    """Read lines from proc stdout until `tag=` appears."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"process exited (code {proc.returncode}) before reporting {tag}"
            )
        line = proc.stdout.readline()
        if not line:
            time.sleep(0.01)
            continue
        line = line.decode() if isinstance(line, bytes) else line
        if line.startswith(tag + "="):
            return line.strip().split("=", 1)[1]
    raise TimeoutError(f"timed out waiting for {tag} from subprocess")


def default_session_dir() -> str:
    return os.path.join(
        "/tmp", "ray_tpu", f"session_{time.strftime('%Y%m%d-%H%M%S')}_{os.getpid()}"
    )


@dataclass
class NodeProcessGroup:
    """Handles to the subprocesses composing one logical node (plus the GCS
    when this is the head)."""

    session_dir: str
    gcs_address: str
    raylet_address: str
    node_id: str
    store_path: str
    gcs_proc: Optional[subprocess.Popen] = None
    raylet_proc: Optional[subprocess.Popen] = None

    def kill(self):
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is not None and proc.poll() is None:
                proc.terminate()
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is not None:
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()


def start_gcs(session_dir: str, host: str = "127.0.0.1", port: int = 0) -> tuple:
    os.makedirs(session_dir, exist_ok=True)
    log = open(os.path.join(session_dir, "gcs.log"), "ab")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu.core.gcs",
            "--host", host, "--port", str(port),
            "--session-dir", session_dir,
        ],
        stdout=subprocess.PIPE,
        stderr=log,
        env=_control_plane_env(),
    )
    log.close()
    address = _read_tagged_line(proc, "GCS_ADDRESS", 30)
    return proc, address


def start_raylet(
    gcs_address: str,
    session_dir: str,
    resources: Dict[str, float],
    labels: Optional[Dict[str, str]] = None,
    host: str = "127.0.0.1",
    store_capacity: int = 0,
    node_id: Optional[str] = None,
    extra_env: Optional[Dict[str, str]] = None,
) -> tuple:
    os.makedirs(session_dir, exist_ok=True)
    log = open(os.path.join(session_dir, "raylet.log"), "ab")
    cmd = [
        sys.executable,
        "-m",
        "ray_tpu.core.raylet",
        "--gcs", gcs_address,
        "--host", host,
        "--resources", json.dumps(resources),
        "--labels", json.dumps(labels or {}),
        "--store-capacity", str(store_capacity),
        "--session-dir", session_dir,
    ]
    if node_id:
        cmd += ["--node-id", node_id]
    env = _control_plane_env()
    if extra_env:
        # slice identity for the raylet and its workers (TPU_NAME etc. —
        # what accelerators/tpu.py turns into slice/head resources)
        env.update(extra_env)
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=log, env=env
    )
    log.close()
    address = _read_tagged_line(proc, "RAYLET_ADDRESS", 60)
    nid = _read_tagged_line(proc, "RAYLET_NODE_ID", 10)
    store_path = f"/dev/shm/rt_store_{nid[:12]}"
    return proc, address, nid, store_path


def _pythonpath_with_pkg() -> str:
    """PYTHONPATH that lets subprocesses import ray_tpu even when the driver
    added the repo to sys.path manually."""
    import ray_tpu

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    parts = [pkg_root] + ([existing] if existing else [])
    return os.pathsep.join(parts)


def _control_plane_env() -> dict:
    """Control-plane processes must never touch the TPU (one process owns the
    chips); pin them to CPU-only jax in case anything imports it."""
    env = dict(os.environ)
    # remember the accelerator platform so TPU-leased workers can be
    # pointed back at it (raylet _accel_env_for); control-plane processes
    # themselves must never touch the TPU
    env.setdefault(
        "RT_TPU_JAX_PLATFORM", os.environ.get("JAX_PLATFORMS") or "tpu"
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _pythonpath_with_pkg()
    return env


def detect_resources(num_cpus=None, num_tpus=None, extra=None) -> Dict[str, float]:
    res: Dict[str, float] = dict(extra or {})
    res["CPU"] = num_cpus if num_cpus is not None else float(os.cpu_count() or 1)
    if num_tpus is None:
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        mgr = TPUAcceleratorManager()
        num_tpus = mgr.num_chips()
        if num_tpus:
            res.update(mgr.extra_resources())
    if num_tpus:
        res["TPU"] = num_tpus
    res.setdefault("memory", float(_total_memory_bytes()))
    return res


def _total_memory_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 << 30
