"""Runtime environments: per-task/actor env vars and code shipping.

Role-equivalent of ray: python/ray/_private/runtime_env/ (the agent at
runtime_env_agent.py:161, working_dir.py, py_modules.py) collapsed into
the lease path: the driver *normalizes* a runtime_env (packaging local
dirs into content-addressed zips stored in GCS KV), the descriptor rides
the lease request, and the worker *applies* it at bind time — fetch,
extract, chdir, sys.path.  Workers are bound to (accelerator env,
runtime env) pairs, so reuse never leaks one env into another (the
reference starts dedicated workers per runtime env for the same reason).

Supported keys: ``env_vars`` (dict), ``working_dir`` (local dir),
``py_modules`` (list of local dirs/files), ``pip`` (per-env virtualenv),
``conda`` (per-env conda env, spec-hashed and cached node-side), and
``container`` (worker spawned inside a docker/podman container with the
session dir mounted).  pip/conda/container are mutually exclusive, like
the reference (ray: _private/runtime_env/{pip,conda,container}.py).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

_MAX_PACKAGE_BYTES = 256 * 1024 * 1024
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}

# caches are scoped by cluster (scope key = GCS address): a package
# uploaded to cluster A must be re-uploaded when the driver reconnects
# to cluster B with a fresh blob store
_uploaded_hashes: set = set()  # (scope, sha) upload dedupe
_normalize_cache: dict = {}  # (scope, json(env)) -> descriptor


def _zip_path(path: str) -> bytes:
    buf = io.BytesIO()
    path = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            for root, dirs, files in os.walk(path):
                dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
                for f in files:
                    full = os.path.join(root, f)
                    zf.write(full, os.path.relpath(full, path))
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(max {_MAX_PACKAGE_BYTES}); ship data via the object store, "
            "not the code package"
        )
    return data


def normalize(
    env: Optional[Dict[str, Any]], kv_put, scope: str = ""
) -> Optional[dict]:
    """Driver side: validate, package, upload; return the wire descriptor.

    ``kv_put(key, value)`` stores a package once (content-addressed);
    ``scope`` identifies the target cluster for cache invalidation.
    """
    if not env:
        return None
    cache_key = (scope, json.dumps(env, sort_keys=True, default=str))
    cached = _normalize_cache.get(cache_key)
    if cached is not None:
        return cached
    unknown = set(env) - {
        "env_vars", "working_dir", "py_modules", "pip", "conda", "container",
    }
    if unknown:
        raise ValueError(f"unknown runtime_env keys: {sorted(unknown)}")
    isolation = [k for k in ("pip", "conda", "container") if env.get(k)]
    if len(isolation) > 1:
        raise ValueError(
            f"runtime_env keys {isolation} are mutually exclusive: pick "
            "ONE of pip (virtualenv over the base image), conda (own "
            "interpreter + native deps), or container (own image)"
        )
    desc: Dict[str, Any] = {}
    conda = env.get("conda")
    if conda is not None:
        # Canonical spec: {"dependencies": [...], "channels": [...]}.
        # Accepts a bare list of package specs, a dict, or a path to an
        # environment.yml (reference: runtime_env/conda.py accepts all
        # three).  Canonicalized + sorted so the node-side cache key is
        # stable across equivalent writings.
        if isinstance(conda, str):
            if not os.path.isfile(conda):
                raise ValueError(
                    f"conda: {conda!r} is not a file; pass a package list, "
                    "a spec dict, or a path to an environment.yml"
                )
            try:
                import yaml  # vendored with many bases; optional

                with open(conda) as f:
                    conda = yaml.safe_load(f)
            except ImportError as e:
                raise ValueError(
                    "conda: reading environment.yml needs pyyaml, which "
                    "this image lacks — pass the spec as a dict or "
                    "package list instead"
                ) from e
        if isinstance(conda, (list, tuple)):
            conda = {"dependencies": list(conda)}
        if not isinstance(conda, dict) or not conda.get("dependencies"):
            raise ValueError(
                "conda must be a package list, a spec dict with "
                "'dependencies', or an environment.yml path"
            )
        deps = conda["dependencies"]
        if not all(isinstance(d, str) for d in deps):
            raise ValueError(
                "conda dependencies must be plain package specs "
                "(nested pip: sections are not supported — use the pip "
                "runtime env for pip packages)"
            )
        desc["conda"] = {
            "dependencies": sorted(deps),
            "channels": sorted(conda.get("channels", [])),
        }
    container = env.get("container")
    if container is not None:
        if isinstance(container, str):
            container = {"image": container}
        if not isinstance(container, dict) or not container.get("image"):
            raise ValueError(
                "container must be an image name or a dict with 'image' "
                "(+ optional 'run_options': list of extra runtime flags)"
            )
        run_opts = container.get("run_options", [])
        if not all(isinstance(o, str) for o in run_opts):
            raise ValueError("container run_options must be strings")
        desc["container"] = {
            "image": container["image"],
            "run_options": list(run_opts),
        }
    pip = env.get("pip")
    if pip:
        # per-env virtualenv (reference: runtime_env/pip.py role): the
        # RAYLET materializes a venv keyed by the requirement list and
        # spawns the env's workers with its interpreter (worker reuse is
        # already partitioned by descriptor_key, so envs never mix)
        if isinstance(pip, dict):
            pip = pip.get("packages", [])
        if not (isinstance(pip, (list, tuple))
                and all(isinstance(p, str) for p in pip)):
            raise ValueError("pip must be a list of requirement strings")
        desc["pip"] = sorted(pip)
    env_vars = env.get("env_vars")
    if env_vars:
        if not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()
        ):
            raise ValueError("env_vars must be str->str")
        desc["env_vars"] = dict(env_vars)

    def upload(path: str) -> str:
        data = _zip_path(path)
        sha = hashlib.sha256(data).hexdigest()[:32]
        if (scope, sha) not in _uploaded_hashes:
            kv_put(sha, data)
            _uploaded_hashes.add((scope, sha))
        return sha

    if env.get("working_dir"):
        desc["working_dir_pkg"] = upload(env["working_dir"])
    if env.get("py_modules"):
        desc["py_module_pkgs"] = [upload(p) for p in env["py_modules"]]
    out = desc or None
    # NB: cached per env DICT, like the reference's once-per-job upload —
    # mutating the directory after the first call does not re-package
    _normalize_cache[cache_key] = out
    return out


def descriptor_key(desc: Optional[dict]) -> str:
    """Stable identity for worker binding/reuse."""
    if not desc:
        return ""
    return hashlib.sha256(
        json.dumps(desc, sort_keys=True).encode()
    ).hexdigest()[:16]


def _extract_dir(sha: str) -> str:
    return os.path.join("/tmp", "ray_tpu", "runtime_envs", sha)


async def apply(desc: dict, kv_get) -> None:
    """Worker side: fetch packages, extract, bind this process to the env.

    ``kv_get`` is an async callable (GCS KV fetch).  Idempotent per
    package (content-addressed extract dirs).
    """
    for k, v in (desc.get("env_vars") or {}).items():
        os.environ[k] = v

    async def fetch_extract(sha: str) -> str:
        target = _extract_dir(sha)
        if not os.path.isdir(target):
            blob = await kv_get(sha)
            if blob is None:
                raise RuntimeError(f"runtime_env package {sha} missing")
            tmp = target + f".tmp{os.getpid()}"
            with zipfile.ZipFile(io.BytesIO(bytes(blob))) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, target)  # atomic: concurrent extracts race
            except OSError:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        return target

    pkgs: List[str] = []
    if desc.get("working_dir_pkg"):
        wd = await fetch_extract(desc["working_dir_pkg"])
        os.chdir(wd)
        pkgs.append(wd)
    for sha in desc.get("py_module_pkgs", ()):
        pkgs.append(await fetch_extract(sha))
    for p in pkgs:
        if p not in sys.path:
            sys.path.insert(0, p)
