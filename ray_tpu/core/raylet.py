"""Raylet: the per-node daemon — worker pool, store owner, object transfer.

Role-equivalent of the reference's raylet (ray: src/ray/raylet/raylet.h:37,
node_manager.h:125, worker_pool.h:156, object_manager/object_manager.h:117)
with a deliberately smaller job: scheduling decisions live in the GCS (see
gcs.py header), so the raylet is (1) a worker process factory with
accelerator-aware reuse, (2) the owner of the node's shm object store, and
(3) the node-to-node object transfer endpoint (PullManager/PushManager
analogue, pull-based).

TPU ownership model: libtpu allows one process per chip set, so TPU leases
carry an explicit chip assignment (TPU_VISIBLE_CHIPS) decided here.  A worker
is forever bound to the first accelerator env it receives (jax initializes
once); idle workers are reused only on exact-match bindings, and idle workers
whose chips conflict with a new allocation are killed (ray's env-var dance at
python/ray/_private/accelerators/tpu.py:174-196 is per-task; here it is a
lease-time contract).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from ray_tpu._native.store import ShmStore, default_capacity
from ray_tpu.common import faults
from ray_tpu.common.config import cfg
from ray_tpu.common.ids import NodeID, WorkerID
from ray_tpu.core import rpc
from ray_tpu.core.errors import FencedError, is_fenced

logger = logging.getLogger(__name__)

#: Pull-source shuffle: one private instance instead of the module-global
#: random state, so the load-spreading shuffle neither perturbs nor is
#: perturbed by seeded user code (and stays outside RT116's
#: unseeded-global-RNG scope if the soak lint ever widens)
_PULL_SHUFFLE_RNG = random.Random()

#: FaultPlan.delay_s's field default — a node.preempt plan that never set
#: delay_s means "use the config drain deadline", not a 50 ms drain
_PLAN_DELAY_DEFAULT = faults.FaultPlan.__dataclass_fields__[
    "delay_s"
].default


@dataclass
class WorkerEntry:
    worker_id: WorkerID
    proc: subprocess.Popen
    conn: Optional[rpc.Connection] = None  # worker's connection to us
    addr: Optional[str] = None  # worker's own rpc server address
    bound_env: Optional[Dict[str, str]] = None  # accelerator env, once set
    rtenv_key: str = ""  # runtime-env binding (core/runtime_env.py)
    venv_key: str = ""   # pip-env interpreter this worker was spawned with
    lease_id: Optional[int] = None
    tpu_chips: tuple = ()
    started_at: float = field(default_factory=time.monotonic)
    leased_at: float = 0.0  # monotonic time of the CURRENT lease grant
    # containerized workers: `docker/podman kill <name>` argv — SIGKILL
    # on `proc` (the run CLIENT) never reaches the container
    container_kill_argv: Optional[list] = None

    @property
    def idle(self) -> bool:
        return self.lease_id is None and self.conn is not None


class Raylet:
    def __init__(
        self,
        gcs_address: str,
        node_id: Optional[NodeID] = None,
        host: str = "127.0.0.1",
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        store_capacity: int = 0,
        session_dir: str = "/tmp/ray_tpu",
    ):
        self.gcs_address = gcs_address
        self.node_id = node_id or NodeID.random()
        self.host = host
        self.labels = labels or {}
        self.session_dir = session_dir
        self.resources = resources or {}
        self.store_path = os.path.join(
            "/dev/shm", f"rt_store_{self.node_id.hex()[:12]}"
        )
        self.store_capacity = store_capacity or default_capacity()
        self.store: Optional[ShmStore] = None
        self.server = rpc.Server(self._handle, host=host, port=0)
        self.gcs: Optional[rpc.Connection] = None
        self.workers: Dict[WorkerID, WorkerEntry] = {}
        self._idle_by_env: Dict[tuple, List[WorkerEntry]] = {}
        self._tpu_chips_free: Set[int] = set(
            range(int(self.resources.get("TPU", 0)))
        )
        self._peer_conns: Dict[str, rpc.Connection] = {}
        self._inflight_pulls: Dict[bytes, asyncio.Future] = {}
        self._tasks: List[asyncio.Task] = []
        self._closing = False
        # Object spilling (reference role: raylet/local_object_manager.h:41
        # SpillObjects + python/ray/_private/external_storage.py).  Primary
        # copies are `protect`ed in the arena (LRU cannot evict them);
        # when the arena passes the high-water mark the spill loop writes
        # the least-recently-used ones to files here, registers the
        # spilled location with the GCS, and drops the arena copy.
        self.spill_dir = os.path.join(
            session_dir, "spill", self.node_id.hex()[:12]
        )
        self._spilled: Dict[bytes, int] = {}  # oid -> size
        self._spilled_bytes = 0
        self._spill_count = 0
        self._restore_count = 0
        self._spill_lock = asyncio.Lock()
        # pip runtime envs: requirement-hash -> creation lock (venvs live
        # under session_dir/pip_envs; see _ensure_pip_env)
        self._pip_env_locks: Dict[str, asyncio.Lock] = {}
        # set by SIGTERM or the shutdown_node RPC; main() awaits it and
        # tears the node down (cluster launcher `down` uses the RPC to
        # drain nodes it has no pid for, e.g. on other hosts)
        self.stop_requested = asyncio.Event()
        # graceful drain: set by the GCS's drain notify (or by the local
        # preemption watcher) — new leases are refused while in-flight
        # work finishes inside the announced deadline
        self.draining = False
        # incarnation fencing: this life's token (assigned by the GCS at
        # registration, carried on every raylet->GCS and peer->raylet
        # RPC); a FencedError reply means the cluster declared this life
        # dead — _fence_self kills the workers, discards the object
        # copies, and re-registers fresh
        self.incarnation = 0
        self._fencing = False
        # peer incarnation watermarks (node hex -> highest incarnation
        # seen, via the "nodes" pubsub channel and peer RPC payloads):
        # an inbound peer RPC below the watermark is rejected
        self._node_incs: Dict[str, int] = {}
        # per-tick add_object_location coalescing (data plane v2): pulls,
        # spill restores and evacuation sweeps started within one loop
        # tick announce through one object_notify_batch rpc instead of a
        # notify per object (see _announce)
        self._announce_buf: list = []
        self._announce_flush = None  # in-flight flush future, if any

    # ---- lifecycle -----------------------------------------------------
    async def start(self):
        os.makedirs(self.session_dir, exist_ok=True)
        if os.path.exists(self.store_path):
            os.unlink(self.store_path)
        self.store = ShmStore(self.store_path, self.store_capacity, create=True)
        await self.server.start()
        # partition plane: this raylet (and every worker it spawns) is
        # the node's logical endpoint
        faults.set_local_endpoint(self.node_id.hex())
        # Reconnecting channel: a GCS crash/restart no longer kills the
        # node — the raylet re-dials, re-registers (same node_id), and the
        # GCS restores cluster state from its checkpoint (gcs.py
        # CheckpointStore).  Workers and their direct client connections
        # keep running through the outage.
        self.gcs = rpc.ReconnectingConnection(
            self.gcs_address, self._handle, name="raylet->gcs",
            on_reconnect=self._register_with_gcs,
            on_give_up=self._on_gcs_lost,
            peer_endpoint="gcs",
        )
        reply = await self.gcs.call("register_node", self._register_payload())
        self.incarnation = int((reply or {}).get("incarnation", 0) or 0)
        # incarnation watermarks for peer->raylet fencing ride the
        # "nodes" pubsub channel (suspect/dead/alive events carry them)
        await self.gcs.call("subscribe", {"channel": "nodes"})
        loop = asyncio.get_running_loop()
        self._tasks.append(loop.create_task(self._heartbeat_loop()))
        self._tasks.append(loop.create_task(self._reaper_loop()))
        if cfg.preempt_poll_interval_s > 0:
            self._tasks.append(loop.create_task(self._preempt_watch_loop()))
        if cfg.memory_monitor_interval_s > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor

            self.memory_monitor = MemoryMonitor(self)
            self._tasks.append(loop.create_task(self.memory_monitor.loop()))
        n_prestart = min(int(self.resources.get("CPU", 0)), cfg.worker_pool_prestart)
        for _ in range(n_prestart):
            self._spawn_worker()
        logger.info(
            "raylet %s up at %s (store %s, %d bytes)",
            self.node_id, self.server.address, self.store_path, self.store_capacity,
        )

    def _register_payload(self, fresh: bool = False) -> dict:
        return {
            "node_id": self.node_id.binary(),
            "address": self.server.address,
            "resources": self.resources,
            "labels": self.labels,
            # claim the current life on reconnects so object copies and
            # leases carry over; None starts a NEW incarnation
            "incarnation": (
                None if fresh or not self.incarnation else self.incarnation
            ),
        }

    async def _register_with_gcs(self, conn):
        """Re-attach to a reborn GCS over a fresh connection.  NB: runs
        inside ReconnectingConnection._ensure — must use ``conn``
        directly (self.gcs.call would deadlock on the redial lock)."""
        try:
            reply = await conn.call("register_node", self._register_payload())
        except rpc.RemoteCallError as e:
            if not is_fenced(e):
                raise
            # declared dead while we were away (partition healed): purge
            # this life's state, then join as a fresh incarnation.  The
            # _fencing guard holds across the purge AND the fresh
            # registration: leases are refused meanwhile, and a
            # concurrent peer-fence (_fence_self off a rejected pull)
            # must not purge a second time — it would destroy the
            # rebuilt arena and kill workers just leased to the new
            # incarnation.  Conversely, if a _fence_self purge is
            # already in flight (it set _fencing before blocking on
            # this redial's lock), skip the purge here and only
            # re-register fresh.
            already_fencing = self._fencing
            self._fencing = True
            try:
                if not already_fencing:
                    await self._purge_for_fence(
                        "re-registration rejected: stale incarnation"
                    )
                reply = await conn.call(
                    "register_node", self._register_payload(fresh=True)
                )
            finally:
                if not already_fencing:
                    self._fencing = False
        self.incarnation = int((reply or {}).get("incarnation", 0) or 0)
        await conn.call("subscribe", {"channel": "nodes"})
        logger.info(
            "raylet %s re-registered with GCS (incarnation %d)",
            self.node_id, self.incarnation,
        )

    def _on_gcs_lost(self):
        if not self._closing:
            logger.error(
                "raylet %s: GCS unreachable past the reconnect budget; "
                "shutting down", self.node_id,
            )
            for w in self.workers.values():
                if w.container_kill_argv:
                    # fire-and-forget: this process is about to _exit and
                    # a terminated run client strands its container
                    try:
                        subprocess.Popen(
                            w.container_kill_argv,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                        )
                    except Exception:
                        pass
                w.proc.terminate()
            os._exit(1)

    async def close(self):
        self._closing = True
        for t in self._tasks:
            t.cancel()
        for w in list(self.workers.values()):
            try:
                w.proc.terminate()
            except Exception:
                pass
        for w in list(self.workers.values()):
            try:
                w.proc.wait(timeout=2)
            except Exception:
                self._hard_kill_worker(w)
        if self.gcs:
            await self.gcs.close()
        await self.server.close()
        if self.store:
            self.store.destroy()

    async def _heartbeat_loop(self):
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            try:
                # a CALL, not a notify: the reply channel is where a
                # zombie learns it was fenced.  urgent=True writes the
                # tiny frame ahead of any per-tick BATCH accumulation
                # and skips transport flow-control waits — a loaded
                # tick must not delay the detector's input (that delay
                # IS the false-positive mode the phi detector absorbs).
                # The timeout is ONE interval: delivery is one-way for
                # liveness (the reply only carries fencing), and a lost
                # heartbeat must not block the next one past the
                # detector's death floor — that would turn a healed
                # sub-threshold partition into a false death.
                await self.gcs.call(
                    "heartbeat",
                    {
                        "node_id": self.node_id.binary(),
                        "incarnation": self.incarnation,
                    },
                    timeout=max(cfg.heartbeat_interval_s, 0.2),
                    urgent=True,
                )
            except rpc.RemoteCallError as e:
                if is_fenced(e):
                    await self._fence_self(str(e.remote_exception))
            except Exception:
                pass
            # collect dead worker processes
            for w in list(self.workers.values()):
                if w.proc.poll() is not None:
                    await self._on_worker_exit(w)

    async def _reaper_loop(self):
        while True:
            await asyncio.sleep(5.0)
            try:
                self.store.reap()
            except Exception:
                pass
            try:
                await self._maybe_spill()
            except Exception:
                logger.exception("spill pass failed")

    # ---- object spilling ------------------------------------------------

    def _spill_path(self, oid: bytes) -> str:
        return os.path.join(self.spill_dir, oid.hex() + ".obj")

    async def _maybe_spill(self, needed_bytes: int = 0,
                           object_bytes: int = 0) -> int:
        """Spill LRU primaries until the arena is under the low-water mark
        (or `needed_bytes` have been freed).  Returns bytes freed.
        ``object_bytes`` (when known) is the size of the single object
        the caller is trying to place — one that can NEVER fit fails
        fast instead of stripping the whole arena for nothing."""
        if not cfg.object_spill_enabled:
            return 0
        async with self._spill_lock:
            st = self.store.stats()
            cap = st["capacity"] or 1
            if object_bytes and object_bytes > cap:
                return 0
            if needed_bytes:
                # clamp instead of refusing: escalating retries may ask
                # for more than capacity while the OBJECT still fits —
                # worst case we spill the whole arena, which is exactly
                # what a near-capacity create needs
                needed_bytes = min(needed_bytes, cap)
                headroom = cap - st["used"]
                shortfall = needed_bytes - headroom
                if shortfall <= 0:
                    # the caller's create failed despite apparent headroom:
                    # fragmentation — spill ~needed_bytes of LRU primaries
                    # so arena_free can merge a contiguous run.  Do NOT
                    # clamp this to the low-water mark: when used is
                    # already below it the clamp would free 0 bytes on
                    # every retry and the fragmented create starves (the
                    # retry loops in _write_to_store / _restore_from_spill
                    # give up on a zero-freed pass).  Spill amount stays
                    # bounded at ~needed_bytes per pass (callers escalate
                    # needed_bytes across retries; min(needed, cap) above
                    # bounds the worst case).
                    shortfall = needed_bytes
                # floor at 0: needed_bytes >= used means the caller needs
                # more than everything currently resident — draining all
                # spillables is then exactly the progress required
                target = max(st["used"] - shortfall, 0)
            elif st["used"] > cfg.object_spill_high_frac * cap:
                target = int(cfg.object_spill_low_frac * cap)
            else:
                return 0
            freed = 0
            for oid, size in self.store.list_spillable():
                if st["used"] - freed <= target:
                    break
                if await self._spill_one(oid, size):
                    freed += size
            return freed

    async def _spill_one(self, oid: bytes, size: int) -> bool:
        pin = self.store.get(oid)
        if pin is None:
            return False
        path = self._spill_path(oid)
        tmp = path + ".tmp"
        try:
            os.makedirs(self.spill_dir, exist_ok=True)
            # write straight from the pinned arena view on a worker thread
            # (copying multi-GB objects on the event loop stalls all RPCs)
            await asyncio.to_thread(self._write_file, tmp, pin.view)
            os.replace(tmp, path)
        except OSError:
            logger.exception("spill write failed for %s", oid.hex()[:12])
            return False
        finally:
            pin.release()
        self._spilled[oid] = size
        self._spilled_bytes += size
        self._spill_count += 1
        try:
            reply = await self.gcs.call("add_spilled_location", {
                "object_id": oid,
                "node_id": self.node_id.binary(),
                "incarnation": self.incarnation,
                "size": size,
            })
        except Exception:
            # GCS unreachable: keep the arena copy authoritative
            self._drop_spill_file(oid)
            return False
        if not (isinstance(reply, dict) and reply.get("ok")):
            # the object was freed while we were writing the file: keep
            # the arena copy (its pending delete reclaims it), drop ours
            self._drop_spill_file(oid)
            return False
        # The file is now the durable primary; the arena copy is cache.
        self.store.protect(oid, False)
        if self.store.delete(oid):
            # arena copy gone: retract the directory entry so pullers
            # don't see this node listed twice (location + spilled)
            try:
                await self.gcs.notify("remove_object_location", {
                    "object_id": oid,
                    "node_id": self.node_id.binary(),
                })
            except Exception:
                pass
        # (delete refuses while a reader holds a pin — fine: the entry is
        # unprotected now, so LRU reclaims it and the location goes stale
        # only until the object is freed)
        return True

    @staticmethod
    def _write_file(path: str, data) -> None:
        with open(path, "wb") as f:
            f.write(data)  # bytes or a pinned memoryview — no extra copy
            f.flush()
            os.fsync(f.fileno())

    def _drop_spill_file(self, oid: bytes) -> None:
        size = self._spilled.pop(oid, None)
        if size is not None:
            self._spilled_bytes -= size
        try:
            os.unlink(self._spill_path(oid))
        except OSError:
            pass

    async def _restore_from_spill(self, oid: bytes) -> bool:
        """Read a spilled object back into the arena (stays spilled on
        disk; the arena copy is a cache until the object is freed)."""
        if oid not in self._spilled:
            return False
        try:
            data = await asyncio.to_thread(
                lambda: open(self._spill_path(oid), "rb").read()
            )
        except OSError:
            logger.exception("spill restore failed for %s", oid.hex()[:12])
            return False
        placed = False
        for attempt in range(3):
            try:
                self._store_put_new(oid, data)
                placed = True
                break
            except Exception:
                # arena full: make room (exact size first, then
                # escalating; _maybe_spill clamps to capacity) — the
                # pull path treats a failed restore as retryable, but
                # succeeding here saves the caller a full round trip
                freed = await self._maybe_spill(
                    needed_bytes=len(data) * (attempt + 1),
                    object_bytes=len(data),
                )
                if not freed and attempt:
                    break
        if not placed:
            return False
        self._restore_count += 1
        await self._announce(oid, len(data))
        return True

    def _read_spilled(self, oid: bytes, offset: int = 0,
                      length: Optional[int] = None) -> Optional[bytes]:
        if oid not in self._spilled:
            return None
        try:
            with open(self._spill_path(oid), "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read(length if length is not None else -1)
        except OSError:
            return None

    # ---- graceful drain / preemption ------------------------------------

    async def rpc_drain(self, conn, p):
        """GCS drain notify: stop accepting leases; in-flight tasks keep
        running and finish inside the announced deadline (the GCS drain
        task waits for their leases to return before declaring the node
        drained)."""
        self.draining = True
        logger.warning(
            "raylet %s draining (%s, deadline %.1fs): refusing new leases",
            self.node_id, p.get("reason"), p.get("deadline_s", 0.0),
        )
        return True

    async def _preempt_watch_loop(self):
        """Preemption watcher: converts an announced termination (spot/
        preemptible notice) into a graceful drain.  Two signal sources:

        - the ``node.preempt`` chaos site — each poll is one hit with
          the node id as context, so a seeded ``FaultPlan`` drives a
          preemption deterministically (``delay_s`` carries the
          announced deadline; 0/default falls back to
          ``cfg.drain_deadline_default_s``);
        - the GCE metadata stub (``RT_PREEMPT_METADATA``; see
          autoscaler/tpu_provider.GceMetadataPreemption), polling the
          instance's ``preempted`` flag the way a real TPU VM would.
        """
        source = None
        if os.environ.get("RT_PREEMPT_METADATA"):
            try:
                from ray_tpu.autoscaler.tpu_provider import (
                    GceMetadataPreemption,
                )

                source = GceMetadataPreemption()
            except Exception:
                logger.exception("metadata preemption source unavailable")
        while True:
            await asyncio.sleep(cfg.preempt_poll_interval_s)
            if self.draining:
                continue  # notice already delivered
            deadline_s = 0.0
            fault_ctl = faults.ACTIVE  # bind once: clear() races the check
            if fault_ctl is not None:
                plan = fault_ctl.hit(
                    faults.SITE_NODE_PREEMPT, self.node_id.hex()
                )
                if plan is not None and plan.action in ("preempt", "error"):
                    # delay_s carries the announced deadline; unset
                    # (FaultPlan's 0.05 "delay" default) or non-positive
                    # falls back to the config default — a fired plan
                    # must always deliver a usable notice (the nth-hit
                    # window is already consumed)
                    d = plan.delay_s
                    if d is None or d <= 0 or d == _PLAN_DELAY_DEFAULT:
                        d = cfg.drain_deadline_default_s
                    deadline_s = d
            if not deadline_s and source is not None:
                try:
                    deadline_s = await asyncio.to_thread(source.poll)
                except Exception:
                    deadline_s = 0.0
            if not deadline_s or deadline_s <= 0:
                continue
            logger.warning(
                "raylet %s: preemption notice, %.1fs to termination — "
                "requesting graceful drain", self.node_id, deadline_s,
            )
            self.draining = True
            try:
                await self.gcs.call(
                    "drain_node",
                    {
                        "node_id": self.node_id.hex(),
                        "reason": "preemption",
                        "deadline_s": deadline_s,
                    },
                )
            except Exception:
                # GCS unreachable: un-arm so the next poll retries the
                # notice (the kill is coming either way; retrying is the
                # only useful move)
                logger.exception("preemption drain request failed")
                self.draining = False

    async def rpc_shutdown_node(self, conn, p):
        """Graceful remote shutdown (ray: `ray down` draining a node the
        caller holds no pid for): main() observes stop_requested and runs
        the same close() path SIGTERM takes — workers killed, arena
        unlinked, node deregistered."""
        self.stop_requested.set()
        return True

    # ---- incarnation fencing --------------------------------------------

    async def _purge_for_fence(self, reason: str):
        """Discard everything this (declared-dead) life owned: workers
        are hard-killed (a named actor must never execute on two nodes
        at once — the replacement is already running elsewhere), the
        shm arena is destroyed and re-created empty (our object copies
        were dropped from the directory at death; serving them again
        would resurrect stale locations), and spill files are deleted."""
        logger.error(
            "raylet %s FENCED (%s): killing %d worker(s), discarding "
            "object copies, re-registering fresh",
            self.node_id, reason, len(self.workers),
        )
        for w in list(self.workers.values()):
            self._hard_kill_worker(w)
        self.workers.clear()
        self._idle_by_env.clear()
        self._tpu_chips_free = set(range(int(self.resources.get("TPU", 0))))
        for oid in list(self._spilled):
            self._drop_spill_file(oid)
        try:
            self.store.destroy()
        except Exception:
            logger.exception("fenced arena teardown failed")
        try:
            self.store = ShmStore(
                self.store_path, self.store_capacity, create=True
            )
        except Exception:
            logger.exception("fenced arena rebuild failed")
        self.draining = False

    async def _fence_self(self, reason: str):
        """A FencedError reached us (stale incarnation — the cluster
        declared this node dead, e.g. across a healed partition): purge
        this life and re-register as a fresh incarnation.  Failure to
        re-register leaves the stale token in place, so the next
        heartbeat's fence reply retries the whole sequence."""
        if self._fencing or self._closing:
            return
        self._fencing = True
        try:
            await self._purge_for_fence(reason)
            reply = await self.gcs.call(
                "register_node", self._register_payload(fresh=True)
            )
            self.incarnation = int((reply or {}).get("incarnation", 0) or 0)
            await self.gcs.call("subscribe", {"channel": "nodes"})
            logger.warning(
                "raylet %s re-joined as incarnation %d",
                self.node_id, self.incarnation,
            )
        except Exception:
            logger.exception(
                "fence recovery failed; retrying on next heartbeat"
            )
        finally:
            self._fencing = False

    def _note_peer_inc(self, p) -> None:
        """peer->raylet fencing: reject RPCs whose sender's incarnation
        sits below this node's watermark (learned from the GCS "nodes"
        pubsub and from peer payloads themselves), and raise the
        watermark on newer tokens."""
        fn, fi = p.get("from_node"), p.get("from_inc")
        if fn is None or fi is None:
            return
        known = self._node_incs.get(fn, 0)
        if fi < known:
            raise FencedError(
                f"peer {fn[:12]} incarnation {fi} is stale (watermark "
                f"{known}): fence yourself and re-register"
            )
        if fi > known:
            self._node_incs[fn] = fi

    def _peer_stamp(self) -> dict:
        return {
            "from_node": self.node_id.hex(),
            "from_inc": self.incarnation,
        }

    async def rpc_publish(self, conn, p):
        """GCS pubsub push (we subscribe to "nodes"): keep incarnation
        watermarks current so stale peers are rejected promptly."""
        if p.get("channel") != "nodes":
            return True
        msg = p.get("message") or {}
        nid, inc = msg.get("node_id"), msg.get("incarnation")
        if nid and inc is not None and inc > self._node_incs.get(nid, 0):
            self._node_incs[nid] = inc
        return True

    # ---- chaos (network-partition installs; see common/faults.py) ------
    async def rpc_chaos_partition(self, conn, p):
        faults.cut_link(p["src"], p["dst"], p.get("duration_s"))
        # workers share the node's network fate: fan the cut out
        for w in list(self.workers.values()):
            if w.conn is not None and not w.conn.closed:
                try:
                    await w.conn.notify("chaos_partition", p)
                except Exception:
                    pass
        return True

    async def rpc_chaos_heal(self, conn, p):
        faults.heal_link(p.get("src"), p.get("dst"))
        for w in list(self.workers.values()):
            if w.conn is not None and not w.conn.closed:
                try:
                    await w.conn.notify("chaos_heal", p)
                except Exception:
                    pass
        return True

    async def rpc_spill_now(self, conn, p):
        """Synchronous pressure relief: a client's create just failed."""
        return await self._maybe_spill(
            needed_bytes=p.get("needed_bytes", 0),
            object_bytes=p.get("object_bytes", 0),
        )

    # ---- dispatch ------------------------------------------------------
    async def _handle(self, conn: rpc.Connection, method: str, p: Any):
        fn = getattr(self, f"rpc_{method}", None)
        if fn is None:
            raise rpc.RpcError(f"raylet: unknown method {method!r}")
        return await fn(conn, p)

    async def rpc_list_worker_tasks(self, conn, p):
        """Live task/actor descriptors from every connected worker
        (state-API fan-out leg; ray: util/state aggregating from raylets)."""
        out = []
        for w in list(self.workers.values()):
            if w.conn is None or w.conn.closed:
                continue
            try:
                st = await w.conn.call("status", {}, timeout=5.0)
            except Exception:
                continue
            st["worker_id"] = w.worker_id.hex()
            st["node_id"] = self.node_id.hex()
            st["leased"] = w.lease_id is not None
            out.append(st)
        return out

    # ---- worker pool ---------------------------------------------------

    def _spawn_worker(self, python_exe: Optional[str] = None,
                      venv_key: str = "",
                      container: Optional[tuple] = None) -> WorkerEntry:
        worker_id = WorkerID.random()
        env = dict(os.environ)
        env["RT_WORKER_ID"] = worker_id.hex()
        env["RT_RAYLET_ADDR"] = self.server.address
        env["RT_GCS_ADDR"] = self.gcs_address
        env["RT_NODE_ID"] = self.node_id.hex()
        env["RT_STORE_PATH"] = self.store_path
        env["RT_SESSION_DIR"] = self.session_dir
        container_kill_argv = None
        if container is not None:
            # (prefix, image) from _container_spawn_prefix: the worker
            # runs inside the container; its env arrives via -e flags
            # (a container does not inherit the raylet's environ).  The
            # container is NAMED so hard kills can target it — SIGKILL
            # on the run client detaches without stopping the container.
            prefix, image = container
            cname = f"rt-worker-{worker_id.hex()[:12]}"
            argv = list(prefix) + ["--name", cname]
            for k, v in env.items():
                if k.startswith(("RT_", "JAX_", "XLA_")):
                    argv += ["-e", f"{k}={v}"]
            argv += [image, "python", "-m", "ray_tpu.core.worker_main"]
            container_kill_argv = [prefix[0], "kill", cname]
        else:
            argv = [
                python_exe or sys.executable, "-m",
                "ray_tpu.core.worker_main",
            ]
        log_path = os.path.join(self.session_dir, f"worker-{worker_id.hex()[:12]}.log")
        logf = open(log_path, "ab")
        proc = subprocess.Popen(
            argv,
            env=env,
            stdout=logf,
            stderr=subprocess.STDOUT,
        )
        logf.close()
        entry = WorkerEntry(
            worker_id=worker_id, proc=proc, venv_key=venv_key,
            container_kill_argv=container_kill_argv,
        )
        self.workers[worker_id] = entry
        return entry

    def _chaos_on_lease_grant(self, w: "WorkerEntry") -> None:
        """Chaos site ``raylet.lease.grant``: fires as a lease is handed
        out.  ``kill`` hard-kills the granted worker — the client's push
        then fails, the lease breaks, and the task-plane retry path
        (requeue → fresh lease → resubmit) runs for real.  This is the
        deterministic nth-hit lease-break the chaos suite drives."""
        fault_ctl = faults.ACTIVE  # re-read: clear() races the caller's check
        if fault_ctl is None:
            return
        plan = fault_ctl.hit(
            faults.SITE_RAYLET_LEASE_GRANT, w.worker_id.hex()
        )
        if plan is not None and plan.action == "kill":
            logger.warning(
                "chaos: killing worker %s on lease grant", w.worker_id
            )
            self._hard_kill_worker(w)

    @staticmethod
    def _hard_kill_worker(w: "WorkerEntry"):
        """SIGKILL that actually reaches containerized workers: the run
        client detaches on SIGKILL without stopping the container, so
        the container is killed by name first.  Fire-and-forget — this
        runs inside async close(); blocking on a wedged container
        runtime daemon would stall the event loop per worker."""
        if w.container_kill_argv:
            try:
                subprocess.Popen(
                    w.container_kill_argv,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            except Exception:
                pass
        try:
            w.proc.kill()
        except Exception:
            pass

    async def _ensure_cached_env(self, kind: str, key: str, build) -> str:
        """Shared scaffolding for isolated-interpreter runtime envs (pip
        venvs, conda envs): env dir keyed under session_dir/<kind>/<key>,
        creation lock-serialized and marker-gated so concurrent leases —
        and a restarted raylet — reuse one env.  ``build(root, python)``
        materializes the env (and must call _inject_parent_site itself
        at the right point); returns the env's python executable."""
        root = os.path.join(self.session_dir, kind, key)
        python = os.path.join(root, "bin", "python")
        marker = os.path.join(root, ".ready")
        if os.path.exists(marker):
            return python
        lock = self._pip_env_locks.setdefault(
            f"{kind}:{key}", asyncio.Lock()
        )
        async with lock:
            if os.path.exists(marker):
                return python

            def run():
                import shutil

                shutil.rmtree(root, ignore_errors=True)
                os.makedirs(os.path.dirname(root), exist_ok=True)
                build(root, python)
                with open(marker, "w") as f:
                    f.write("ok")

            try:
                await asyncio.to_thread(run)
            except Exception as e:
                raise rpc.RpcError(
                    f"{kind.rstrip('s').replace('_', ' ')} setup failed: "
                    f"{e}"
                ) from e
            return python

    async def _ensure_pip_env(self, rtenv: dict) -> str:
        """Materialize (once) a virtualenv for a pip runtime env; returns
        its python executable (reference role:
        python/ray/_private/runtime_env/pip.py PipProcessor).  The venv
        uses --system-site-packages so the base image's jax/numpy stay
        importable; isolation comes from the venv's OWN site-packages
        shadowing them where the requirements overlap."""
        import hashlib
        import json as _json

        reqs = list(rtenv["pip"])
        key = hashlib.sha256(_json.dumps(reqs).encode()).hexdigest()[:16]

        def build(root, python):
            subprocess.run(
                [sys.executable, "-m", "venv",
                 "--system-site-packages", root],
                check=True, capture_output=True,
                timeout=cfg.pip_env_install_timeout_s,
            )
            # injection BEFORE install: --no-build-isolation source
            # builds need setuptools from the parent site
            _inject_parent_site(root)
            r = subprocess.run(
                [python, "-m", "pip", "install",
                 "--no-build-isolation", *reqs],
                capture_output=True, text=True,
                timeout=cfg.pip_env_install_timeout_s,
            )
            if r.returncode != 0:
                raise RuntimeError(
                    f"pip install {reqs} failed: {r.stderr[-800:]}"
                )

        return await self._ensure_cached_env("pip_envs", key, build)

    async def _ensure_conda_env(self, rtenv: dict) -> str:
        """Materialize (once) a conda env for a conda runtime env;
        returns its python executable.  Keyed by the canonical spec hash
        (reference role: python/ray/_private/runtime_env/conda.py —
        env-spec hashing + cached env creation + runtime injection).
        The conda executable comes from RT_CONDA_EXE or PATH
        (conda/mamba/micromamba); a node without one rejects the lease
        with an actionable error."""
        import hashlib
        import json as _json
        import shutil

        spec = rtenv["conda"]
        exe = cfg.conda_exe or next(
            (e for e in ("conda", "mamba", "micromamba") if shutil.which(e)),
            None,
        )
        if exe is None or not shutil.which(exe):
            raise rpc.RpcError(
                "conda runtime env requested but no conda executable was "
                "found on this node (looked for RT_CONDA_EXE, conda, "
                "mamba, micromamba on PATH). Install miniconda/micromamba "
                "on every node, or use pip=[...] (virtualenv over the "
                "base image) / container={'image': ...} instead."
            )
        key = hashlib.sha256(
            _json.dumps(spec, sort_keys=True).encode()
        ).hexdigest()[:16]

        def build(root, python):
            cmd = [shutil.which(exe), "create", "--yes", "-p", root]
            for ch in spec.get("channels", []):
                cmd += ["-c", ch]
            cmd += spec["dependencies"]
            r = subprocess.run(
                cmd, capture_output=True, text=True,
                timeout=cfg.pip_env_install_timeout_s,
            )
            if r.returncode != 0:
                raise RuntimeError(
                    f"{exe} create failed for {spec['dependencies']}: "
                    f"{r.stderr[-800:]}"
                )
            if not os.path.exists(python):
                raise RuntimeError(
                    f"conda env at {root} has no bin/python — add an "
                    "explicit python dependency to the spec (e.g. "
                    "'python=3.12')"
                )
            _inject_parent_site(root)

        return await self._ensure_cached_env("conda_envs", key, build)

    def _container_spawn_prefix(self, rtenv: dict) -> list:
        """argv prefix that wraps the worker command in a container
        (reference role: python/ray/_private/runtime_env/container.py).
        The session dir, /tmp (spill + runtime-env extracts), and /dev/shm
        (the object arena) are shared with the host, and the host network
        is used so the worker's TCP endpoints are directly reachable."""
        import shutil

        runtime = cfg.container_runtime or next(
            (r for r in ("podman", "docker") if shutil.which(r)), None
        )
        if runtime is None or not shutil.which(runtime):
            raise rpc.RpcError(
                "container runtime env requested but no container runtime "
                "was found on this node (looked for RT_CONTAINER_RUNTIME, "
                "podman, docker on PATH). Install one, or use pip/conda "
                "runtime envs instead."
            )
        desc = rtenv["container"]
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )  # .../ray_tpu
        repo_root = os.path.dirname(pkg_root)
        prefix = [
            # --init: an init shim as PID1 forwards SIGTERM/SIGKILL to
            # the worker — without it the in-container python is PID1
            # (default signal dispositions ignored), the raylet's kill
            # paths only hit the `docker run` CLIENT, and the container
            # (plus its leased chips) leaks forever
            shutil.which(runtime), "run", "--rm", "--init",
            "--network=host", "--ipc=host",
            "-v", f"{self.session_dir}:{self.session_dir}",
            "-v", "/tmp:/tmp",
            "-v", f"{repo_root}:{repo_root}:ro",
            "-e", f"PYTHONPATH={repo_root}",
        ]
        prefix += desc.get("run_options", [])
        # image appended by _spawn_worker AFTER the worker's -e env flags
        return prefix, desc["image"]

    async def rpc_worker_ready(self, conn: rpc.Connection, p):
        """A spawned worker reports in with its own server address."""
        wid = WorkerID(p["worker_id"])
        w = self.workers.get(wid)
        if w is None:
            raise rpc.RpcError("unknown worker")
        w.conn = conn
        w.addr = p["address"]
        conn.peer_info["worker_id"] = wid
        key = _env_key(w.bound_env, w.rtenv_key) if w.bound_env else ()
        self._idle_by_env.setdefault(key, []).append(w)
        return True

    async def _wait_for_worker(self, w: WorkerEntry):
        deadline = time.monotonic() + cfg.worker_start_timeout_s
        while w.conn is None:
            if time.monotonic() > deadline:
                raise rpc.RpcError("worker failed to start in time")
            if w.proc.poll() is not None:
                raise rpc.RpcError(
                    f"worker process exited at startup (code {w.proc.returncode}); "
                    f"see {self.session_dir}/worker-{w.worker_id.hex()[:12]}.log"
                )
            await asyncio.sleep(0.01)

    def _accel_env_for(self, resources: Dict[str, float]) -> Dict[str, str]:
        """Accelerator visibility env for a lease (TPU chips or CPU-only)."""
        n_tpu = int(resources.get("TPU", 0))
        if n_tpu <= 0 and resources.get("TPU", 0) > 0:
            n_tpu = 1  # fractional chip -> whole chip visibility
        if n_tpu > 0:
            if len(self._tpu_chips_free) < n_tpu:
                raise rpc.RpcError(
                    f"TPU chips exhausted: want {n_tpu}, free {len(self._tpu_chips_free)}"
                )
            chips = sorted(self._tpu_chips_free)[:n_tpu]
            for c in chips:
                self._tpu_chips_free.discard(c)
            return {
                "TPU_VISIBLE_CHIPS": ",".join(map(str, chips)),
                "_RT_TPU_CHIPS": ",".join(map(str, chips)),
                # undo the control-plane cpu pin for chip-holding workers
                "JAX_PLATFORMS": os.environ.get(
                    "RT_TPU_JAX_PLATFORM", "tpu"
                ),
            }
        return {"JAX_PLATFORMS": "cpu"}

    def _release_accel_env(self, env: Dict[str, str]):
        chips = env.get("_RT_TPU_CHIPS")
        if chips:
            for c in chips.split(","):
                self._tpu_chips_free.add(int(c))

    def _find_idle_tpu_worker(
        self, n_tpu: int, rtenv_key: str = ""
    ) -> Optional[WorkerEntry]:
        """An idle worker already bound to exactly n_tpu chips — reusing
        it avoids allocating fresh chips (which may all be bound to such
        idle workers; the old chips stay with the worker by design)."""
        for pool in self._idle_by_env.values():
            while pool:
                cand = pool[-1]
                if (
                    cand.proc.poll() is not None
                    or cand.conn is None
                    or cand.conn.closed
                ):
                    pool.pop()
                    continue
                if len(cand.tpu_chips) == n_tpu and cand.rtenv_key == rtenv_key:
                    pool.pop()
                    return cand
                break  # pools are homogeneous per binding
        return None

    async def _evict_idle_chip_holders(self, n_tpu_needed: int):
        """Kill idle workers holding chips until n_tpu_needed are free."""
        for pool in list(self._idle_by_env.values()):
            for cand in list(pool):
                if len(self._tpu_chips_free) >= n_tpu_needed:
                    return
                if cand.tpu_chips and cand.idle:
                    pool.remove(cand)
                    await self._on_worker_exit(cand, kill=True)

    async def rpc_lease_worker(self, conn: rpc.Connection, p):
        """GCS asks for a worker bound to `resources` (+ runtime env).
        Returns its address."""
        from ray_tpu.core import runtime_env as rtenv_mod

        if self.draining or self._fencing:
            # belt-and-braces with the GCS-side exclusion: a grant that
            # was in flight when the drain notify landed must not bind a
            # fresh worker to a node about to be terminated (or one
            # mid-fence, whose workers are being purged)
            raise rpc.RpcError(
                f"node {self.node_id.hex()[:12]} is "
                f"{'draining' if self.draining else 'fencing'}; "
                f"lease refused"
            )
        resources = p["resources"]
        rtenv = p.get("runtime_env")
        rtenv_key = rtenv_mod.descriptor_key(rtenv)
        venv_python: Optional[str] = None
        venv_key = ""
        container: Optional[tuple] = None
        if rtenv and rtenv.get("pip"):
            venv_python = await self._ensure_pip_env(rtenv)
            venv_key = rtenv_key
        elif rtenv and rtenv.get("conda"):
            venv_python = await self._ensure_conda_env(rtenv)
            venv_key = rtenv_key
        elif rtenv and rtenv.get("container"):
            container = self._container_spawn_prefix(rtenv)
            venv_key = rtenv_key  # containerized workers never mix pools
        n_tpu = int(resources.get("TPU", 0))
        if n_tpu <= 0 and resources.get("TPU", 0) > 0:
            n_tpu = 1
        if n_tpu > 0:
            # chip-bound reuse must come BEFORE allocation: the free set
            # may be empty precisely because idle workers hold the chips
            w = self._find_idle_tpu_worker(n_tpu, rtenv_key)
            if w is None and len(self._tpu_chips_free) < n_tpu:
                # no compatible idle worker and not enough free chips:
                # evict idle chip holders bound to other envs (the
                # docstring contract: conflicting idle workers are killed)
                await self._evict_idle_chip_holders(n_tpu)
            if w is not None:
                w.lease_id = p["lease_id"]
                w.leased_at = time.monotonic()
                if faults.ACTIVE is not None:
                    self._chaos_on_lease_grant(w)
                return {
                    "worker_id": w.worker_id.binary(),
                    "worker_addr": w.addr,
                    "accelerator_env": {
                        k: v
                        for k, v in (w.bound_env or {}).items()
                        if not k.startswith("_")
                    },
                }
        accel_env = self._accel_env_for(resources)
        key = _env_key(accel_env, rtenv_key)
        # exact-match idle worker?
        w: Optional[WorkerEntry] = None
        pool = self._idle_by_env.get(key, [])
        while pool:
            cand = pool.pop()
            if cand.proc.poll() is None and cand.conn and not cand.conn.closed:
                w = cand
                break
        if w is None:
            # fresh workers (no binding yet) can take any env — but the
            # INTERPRETER is fixed at spawn, so a plain worker can never
            # serve a pip env (nor the reverse)
            pool = self._idle_by_env.get(_env_key(None), [])
            mismatched = []
            while pool:
                cand = pool.pop()
                if cand.venv_key != venv_key:
                    mismatched.append(cand)
                    continue
                if cand.proc.poll() is None and cand.conn and not cand.conn.closed:
                    w = cand
                    break
            pool.extend(mismatched)
        if w is None:
            logger.info(
                "lease %s: no idle worker for key=%s (pools: %s) — spawning",
                p["lease_id"], key,
                {k: len(v) for k, v in self._idle_by_env.items()},
            )
            w = self._spawn_worker(python_exe=venv_python,
                                   venv_key=venv_key,
                                   container=container)
            await self._wait_for_worker(w)
            # worker_ready put the fresh worker in the idle pool; it is being
            # handed out right now, so pull it back out
            for pool in self._idle_by_env.values():
                if w in pool:
                    pool.remove(w)
        if w.bound_env is None:
            try:
                await w.conn.call(
                    "bind_env", {"env": accel_env, "runtime_env": rtenv}
                )
            except Exception:
                # failed bind (e.g. missing runtime-env package): the
                # chips allocated above and the worker itself must not
                # leak — refund and retire it
                self._release_accel_env(accel_env)
                await self._on_worker_exit(w, kill=True)
                raise
            w.bound_env = accel_env
            w.rtenv_key = rtenv_key
            w.tpu_chips = tuple(
                int(c)
                for c in accel_env.get("_RT_TPU_CHIPS", "").split(",")
                if c
            )
        else:
            # reused exact-match worker: give back the duplicate allocation
            self._release_accel_env(accel_env)
        w.lease_id = p["lease_id"]
        w.leased_at = time.monotonic()
        if faults.ACTIVE is not None:
            self._chaos_on_lease_grant(w)
        return {
            "worker_id": w.worker_id.binary(),
            "worker_addr": w.addr,
            "accelerator_env": {
                k: v for k, v in (w.bound_env or {}).items() if not k.startswith("_")
            },
        }

    async def rpc_release_worker(self, conn: rpc.Connection, p):
        wid = WorkerID(p["worker_id"])
        w = self.workers.get(wid)
        if w is None:
            return True
        w.lease_id = None
        if p.get("broken") or w.proc.poll() is not None or (
            w.conn is None or w.conn.closed
        ):
            await self._on_worker_exit(w, kill=True)
            return True
        self._idle_by_env.setdefault(
            _env_key(w.bound_env, w.rtenv_key), []
        ).append(w)
        return True

    async def _on_worker_exit(
        self, w: WorkerEntry, kill: bool = False,
        reason: Optional[str] = None,
    ):
        self.workers.pop(w.worker_id, None)
        for pool in self._idle_by_env.values():
            if w in pool:
                pool.remove(w)
        if w.bound_env:
            self._release_accel_env(w.bound_env)
        if kill and w.proc.poll() is None:
            try:
                w.proc.terminate()
            except Exception:
                pass
        if reason is None:
            reason = f"exit code {w.proc.poll()}"
        try:
            await self.gcs.notify(
                "worker_died",
                {"worker_id": w.worker_id.binary(), "reason": reason,
                 "node_id": self.node_id.binary(),
                 "incarnation": self.incarnation},
            )
        except Exception:
            pass

    # ---- object plane --------------------------------------------------
    async def rpc_pull_object(self, conn: rpc.Connection, p):
        """Local runtime asks us to fetch an object into the node store.

        (ray: object_manager pull_manager.h:52 analogue, pull-based only.)
        Concurrent requests for one object coalesce into a single
        transfer (several tasks landing on a node with the same large
        argument is the broadcast-ingest common case)."""
        oid: bytes = p["object_id"]
        if self.store.contains(oid):
            return True
        existing = self._inflight_pulls.get(oid)
        if existing is not None:
            return await asyncio.shield(existing)
        fut = asyncio.get_running_loop().create_future()
        self._inflight_pulls[oid] = fut
        try:
            ok = await self._pull_object_inner(oid, p)
        except BaseException:
            ok = False
            raise
        finally:
            self._inflight_pulls.pop(oid, None)
            if not fut.done():
                fut.set_result(ok)
        return ok

    async def _pull_object_inner(self, oid: bytes, p) -> bool:
        reply = await self.gcs.call(
            "get_object_locations",
            {"object_id": oid, "timeout": p.get("timeout", 30.0)},
        )
        locations = reply["locations"]
        spilled = reply.get("spilled")
        had_spill_here = False
        if spilled is not None and spilled["node_id"] == self.node_id.hex():
            # our own disk holds it: restore locally, no network
            had_spill_here = True
            if await self._restore_from_spill(oid):
                return True
        elif spilled is not None and spilled["node_id"] not in {
            loc["node_id"] for loc in locations
        }:
            # the spilling node serves fetches straight from its file
            locations = locations + [spilled]
        if not locations:
            # "retry": the directory knows a copy exists (our spill file,
            # restore transiently failed under arena pressure) — the
            # caller must NOT treat this as object loss
            return "retry" if had_spill_here else False
        # Shuffle: under a broadcast (N nodes pulling one seeder's object)
        # each completed pull registers a new location, and randomized
        # source choice spreads the remaining pulls across all replicas —
        # an emergent broadcast tree instead of N full reads of one node
        # (ray: push_manager.h broadcast role, inverted pull-side).
        peers = [
            loc for loc in locations if loc["node_id"] != self.node_id.hex()
        ]
        _PULL_SHUFFLE_RNG.shuffle(peers)
        # health plane: non-suspect copies first (stable sort keeps the
        # shuffle within each class) — a failure-suspected replica costs
        # a full transfer timeout per attempt, so it is the last resort
        peers.sort(key=lambda loc: bool(loc.get("suspect")))
        if not peers and self.store.contains(oid):
            return True
        last_err = None
        transient = had_spill_here
        for loc in peers:
            try:
                if await self._pull_from(oid, loc, peers):
                    return True
                # the peer ANSWERED but had nothing to serve: it may be
                # mid-restore/mid-spill — retryable
                transient = True
            except (rpc.ConnectionLost, ConnectionError, OSError) as e:
                # dead peer with a stale location: NOT retryable — let
                # the caller fall through to lineage reconstruction
                last_err = e
                continue
            except Exception as e:
                if is_fenced(e):
                    # a peer rejected OUR incarnation: this whole life
                    # is stale — fence now (kills workers, discards
                    # copies); the pull fails with the node's old life
                    asyncio.get_running_loop().create_task(
                        self._fence_self("peer rejected our incarnation")
                    )
                    return False
                last_err = e
                transient = True
                continue
        if last_err:
            logger.warning("pull of %s failed: %r", oid.hex()[:12], last_err)
        return "retry" if transient else False

    async def _pull_from(self, oid: bytes, loc, all_peers) -> bool:
        """Fetch one object from `loc` (chunked + pipelined when large,
        striped across additional replicas when available)."""
        peer = await self._peer(loc["address"], loc.get("node_id"))
        # every peer->raylet RPC carries the sender's incarnation: a
        # zombie's fetch is rejected (FencedError) by any peer whose
        # watermark advanced past the dead life
        stamp = self._peer_stamp()
        meta = await peer.call(
            "fetch_object_meta", {"object_id": oid, **stamp},
            timeout=cfg.rpc_call_timeout_s,
        )
        if meta is None:
            return False
        size = meta["size"]
        chunk = cfg.transfer_chunk_bytes
        if size <= chunk:
            data = await peer.call(
                "fetch_object", {"object_id": oid, **stamp},
                timeout=cfg.rpc_call_timeout_s,
            )
            if data is None:
                return False
            self._store_put_new(oid, data)
            await self._announce(oid, size)
            return True
        # large object: write chunks straight into the shm allocation,
        # several in flight, round-robining across known replicas
        try:
            view = self.store.create(oid, size)
        except Exception:
            from ray_tpu._native.store import ObjectExistsError

            if self.store.contains(oid):
                return True
            raise
        sources = [peer]
        for other in all_peers:
            if other is loc:
                continue
            try:
                sources.append(
                    await self._peer(other["address"], other.get("node_id"))
                )
            except Exception:
                continue
        offsets = list(range(0, size, chunk))
        sem = asyncio.Semaphore(cfg.transfer_inflight_chunks)

        async def fetch_one(i: int, off: int):
            src = sources[i % len(sources)]
            length = min(chunk, size - off)
            async with sem:
                data = None
                try:
                    data = await src.call(
                        "fetch_object_chunk",
                        {"object_id": oid, "offset": off, "length": length,
                         **stamp},
                        timeout=cfg.rpc_call_timeout_s,
                    )
                except Exception:
                    pass  # replica died mid-transfer: fall through
                if (data is None or len(data) != length) and src is not peer:
                    data = await peer.call(
                        "fetch_object_chunk",
                        {"object_id": oid, "offset": off, "length": length,
                         **stamp},
                        timeout=cfg.rpc_call_timeout_s,
                    )
                if data is None or len(data) != length:
                    raise rpc.RpcError(
                        f"chunk {off}+{length} of {oid.hex()[:12]} unavailable"
                    )
                view[off:off + length] = data

        # return_exceptions: every fetch task must have FINISHED before the
        # allocation can be aborted — a cancelled-but-running writer on a
        # released memoryview would corrupt the arena
        results = await asyncio.gather(
            *(fetch_one(i, off) for i, off in enumerate(offsets)),
            return_exceptions=True,
        )
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            try:
                self.store.abort(oid)
            except Exception:
                pass
            raise errs[0]
        self.store.seal(oid)
        await self._announce(oid, size)
        return True

    def _store_put_new(self, oid: bytes, data) -> None:
        try:
            self.store.put(oid, data)
        except Exception as e:
            from ray_tpu._native.store import ObjectExistsError

            if not isinstance(e, ObjectExistsError):
                raise

    async def _announce(self, oid: bytes, size: int) -> None:
        """Register an arena copy with the directory.  Announces buffered
        within one loop tick ride a single object_notify_batch rpc (an
        evacuation sweep or a burst of restores was paying one GCS notify
        per object); awaiting the shared flush future keeps the v1
        contract that the announce is on the wire before the caller
        proceeds.  The first announcer of a tick becomes the flusher: it
        yields once (so same-tick announcers land in the buffer behind
        it), swaps the buffer out, and sends one batch; everyone else
        just awaits the flusher's future."""
        self._announce_buf.append((
            "add_object_location",
            {
                "object_id": oid,
                "node_id": self.node_id.binary(),
                "incarnation": self.incarnation,
                "size": size,
            },
        ))
        fut = self._announce_flush
        if fut is not None:
            await fut
            return
        self._announce_flush = fut = (
            asyncio.get_running_loop().create_future()
        )
        try:
            await asyncio.sleep(0)
        except BaseException as e:
            # cancelled before the swap: waiters' items are still
            # buffered — fail them so nobody parks on a dead future
            self._announce_flush = None
            fut.set_exception(e)
            fut.exception()
            raise
        # swap + clear BEFORE the notify awaits: an announcer arriving
        # mid-send must become the next flusher, not park on a future
        # whose batch does not contain its item
        items, self._announce_buf = self._announce_buf, []
        self._announce_flush = None
        try:
            if self.gcs is not None and items:
                if len(items) == 1:
                    await self.gcs.notify(items[0][0], items[0][1])
                else:
                    await self.gcs.notify(
                        "object_notify_batch", {"items": items}
                    )
        except BaseException as e:
            fut.set_exception(e)
            fut.exception()  # mark retrieved: waiters may all be gone
            raise
        fut.set_result(None)

    async def rpc_fetch_object(self, conn: rpc.Connection, p):
        """A remote raylet asks for an object's bytes (small objects)."""
        self._note_peer_inc(p)
        oid = p["object_id"]
        pin = self.store.get(oid)
        if pin is None:
            return await asyncio.to_thread(self._read_spilled, oid)
        try:
            return bytes(pin.view)
        finally:
            pin.release()

    async def rpc_fetch_object_meta(self, conn: rpc.Connection, p):
        self._note_peer_inc(p)
        oid = p["object_id"]
        pin = self.store.get(oid)
        if pin is None:
            size = self._spilled.get(oid)
            return None if size is None else {"size": size}
        try:
            return {"size": pin.view.nbytes}
        finally:
            pin.release()

    async def rpc_fetch_object_chunk(self, conn: rpc.Connection, p):
        self._note_peer_inc(p)
        oid = p["object_id"]
        off, ln = p["offset"], p["length"]
        pin = self.store.get(oid)
        if pin is None:
            # spilled: serve the byte range straight from the file — no
            # arena restore on the serving node
            return await asyncio.to_thread(self._read_spilled, oid, off, ln)
        try:
            return bytes(pin.view[off:off + ln])
        finally:
            pin.release()

    async def rpc_delete_objects(self, conn: rpc.Connection, p):
        for oid in p["object_ids"]:
            if not self.store.delete(oid):
                # a reader still pins it (zero-copy get in some process):
                # the delete is refused, and nothing ever retries it.
                # Clear the primary bit so the entry becomes ordinary LRU
                # prey the moment the last pin drops — a freed object
                # must not stay resident as an undeletable protected
                # primary for the life of the node.
                self.store.protect(oid, on=False)
            self._drop_spill_file(oid)
        return True

    async def rpc_store_stats(self, conn: rpc.Connection, p):
        st = self.store.stats()
        st["spilled_bytes"] = self._spilled_bytes
        st["spilled_objects"] = len(self._spilled)
        st["spill_count"] = self._spill_count
        st["restore_count"] = self._restore_count
        return st

    async def _peer(self, address: str,
                    node_hex: Optional[str] = None) -> rpc.Connection:
        c = self._peer_conns.get(address)
        if c is None or c.closed:
            c = await rpc.connect(address, name=f"raylet->{address}",
                                  peer_endpoint=node_hex)
            self._peer_conns[address] = c
        elif node_hex is not None and c.peer_endpoint is None:
            c.peer_endpoint = node_hex
        return c


def _inject_parent_site(root: str) -> None:
    """Make ray_tpu + the base image's packages importable inside an
    isolated env at ``root`` (pip venv or conda env): a .pth in each of
    the env's site-packages appends the ray_tpu package root and this
    interpreter's site dirs AFTER the env's own site-packages — the
    env's dependencies shadow ours where they overlap, but workers can
    always import the runtime (reference: runtime_env/conda.py
    _inject_ray_to_conda_site; shared here so pip and conda injection
    semantics can never diverge)."""
    import glob

    pkg_parent = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    parents = [pkg_parent] + [
        p for p in sys.path if p.endswith("site-packages")
    ]
    for vs in glob.glob(
        os.path.join(root, "lib", "python*", "site-packages")
    ):
        with open(os.path.join(vs, "_rt_parent_env.pth"), "w") as f:
            f.write("\n".join(parents) + "\n")


def _env_key(env: Optional[Dict[str, str]], rtenv_key: str = "") -> tuple:
    if env is None and not rtenv_key:
        return ()
    return (tuple(sorted((env or {}).items())), rtenv_key)


# --------------------------------------------------------------------------
# Entrypoint
# --------------------------------------------------------------------------


def main():
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--gcs", required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--node-id", default="")
    ap.add_argument("--resources", default="{}")
    ap.add_argument("--labels", default="{}")
    ap.add_argument("--store-capacity", type=int, default=0)
    ap.add_argument("--session-dir", default="/tmp/ray_tpu")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="[raylet] %(levelname)s %(message)s")

    # SIGUSR1 → dump all thread stacks to stderr (the raylet log): the
    # zero-dependency "where is it stuck" probe (reference role: py-spy
    # via the dashboard reporter)
    import faulthandler
    import signal as _signal

    faulthandler.register(_signal.SIGUSR1)

    async def run():
        import signal

        # Graceful SIGTERM: kill workers and unlink the shm arena — node
        # removal must not leak /dev/shm store files.  Installed BEFORE
        # start(): the parent can observe the node's GCS registration (made
        # inside start()) and send SIGTERM before this coroutine resumes.
        raylet = Raylet(
            gcs_address=args.gcs,
            node_id=NodeID.from_hex(args.node_id) if args.node_id else None,
            host=args.host,
            resources=json.loads(args.resources),
            labels=json.loads(args.labels),
            store_capacity=args.store_capacity,
            session_dir=args.session_dir,
        )
        asyncio.get_running_loop().add_signal_handler(
            signal.SIGTERM, raylet.stop_requested.set
        )
        await raylet.start()
        print(f"RAYLET_ADDRESS={raylet.server.address}", flush=True)
        print(f"RAYLET_NODE_ID={raylet.node_id.hex()}", flush=True)
        await raylet.stop_requested.wait()
        await raylet.close()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
