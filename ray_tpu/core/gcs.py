"""GCS: the cluster-global control plane.

Role-equivalent of the reference's GCS server (ray:
src/ray/gcs/gcs_server/gcs_server.h:78 and the managers under it —
GcsNodeManager, GcsActorManager gcs_actor_manager.h:281, GcsJobManager,
GcsKvManager, GcsHealthCheckManager) plus the *global* half of scheduling.

Design difference from the reference, on purpose: the reference scatters
scheduling across per-node raylets with spillback (raylet/scheduling/
cluster_task_manager.h) because its clusters are huge and heterogeneous.
A TPU cluster is a few hundred hosts arranged in slices, and gang placement
is the common case — so scheduling here is GCS-centric: submitters lease
workers from the GCS scheduler (amortized by client-side lease reuse), and
the raylet is just a worker factory.  This removes the lease-spillback
round-trips entirely and makes gang (slice) placement a single atomic
decision.

All state is in-memory; persistence/HA hooks live behind `CheckpointStore`
(flushed on change, reloadable on restart — the reference's Redis-backed
StoreClient analogue, gcs/store_client/store_client.h).
"""

from __future__ import annotations

import asyncio
import copy
import logging
import os
import time
from collections import OrderedDict, deque
from typing import Deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.common.config import cfg
from ray_tpu.common.health import (
    PhiAccrualDetector,
    death_confirmed,
    is_suspect,
)
from ray_tpu.common.constants import (
    PG_CREATED,
    PG_PENDING,
    PG_REMOVED,
    PG_RESCHEDULING,
    PG_STRATEGIES,
)
from ray_tpu.common.ids import ActorID, JobID, NodeID, PlacementGroupID, WorkerID
from ray_tpu.common.resources import ResourceSet
from ray_tpu.core import rpc
from ray_tpu.core.errors import FencedError

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# Tables
# --------------------------------------------------------------------------


@dataclass
class NodeEntry:
    node_id: NodeID
    address: str  # raylet rpc address
    resources_total: ResourceSet
    resources_available: ResourceSet
    labels: Dict[str, str]
    conn: rpc.Connection
    alive: bool = True
    # adaptive failure detection (common/health.py): phi crossed the
    # suspect threshold — the node is DEPRIORITIZED (leases, pulls,
    # serve routing) but nothing is killed until phi confirms death.
    # Cleared the moment a heartbeat arrives.
    suspect: bool = False
    # monotonically-increasing life counter, bumped on every fresh
    # (re)registration and on _on_node_death — the fencing token: RPCs
    # carrying a stale incarnation are rejected with FencedError, so a
    # zombie raylet on the far side of a healed partition can never
    # keep serving objects or leases alongside its replacement
    incarnation: int = 0
    draining: bool = False  # drain requested: stop scheduling onto it
    # drain protocol v2 (rpc_drain_node): why and until when
    drain_reason: Optional[str] = None  # "idle" | "preemption"
    drain_status: Optional[dict] = None  # progress; see _drain_node
    # lease_worker calls currently awaiting this node's raylet: a grant
    # issued just before a drain began is not in self.leases yet, and
    # the drain's settle phase must not conclude "no work here" while
    # one is in flight (its task would dispatch onto the node after the
    # final evacuation sweep and be lost to the kill)
    inflight_grants: int = 0
    last_heartbeat: float = field(default_factory=time.monotonic)

    # Write-through scheduler index: every assignment to a field the
    # scheduler scores by re-buckets this node (class attrs, not dataclass
    # fields — set per-instance by Scheduler.index_node).
    _sched = None
    _bucket = None

    def __setattr__(self, name, value):
        if name == "resources_available":
            old = getattr(self, "resources_available", None)
            object.__setattr__(self, name, value)
            sched = self._sched
            if sched is not None:
                sched.note_available_change(self, old, value)
            return
        object.__setattr__(self, name, value)
        if name in ("alive", "draining", "conn", "suspect"):
            sched = self._sched
            if sched is not None:
                sched.rebucket(self)


@dataclass
class LeaseEntry:
    lease_id: int
    node_id: NodeID
    worker_id: WorkerID
    worker_addr: str
    resources: ResourceSet
    client_conn: rpc.Connection  # the submitter holding the lease
    actor_id: Optional[ActorID] = None  # set for actor-dedicated leases
    # (pg_id, bundle_index) when the lease draws from a placement-group
    # bundle instead of the node's general pool
    pg_ref: Optional[Tuple[PlacementGroupID, int]] = None


RUNNING_JOB = "RUNNING"
SUCCEEDED_JOB = "SUCCEEDED"
FAILED_JOB = "FAILED"
STOPPED_JOB = "STOPPED"

ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"

@dataclass
class PlacementGroupEntry:
    """A gang reservation: bundles of resources carved out of nodes.

    Role-equivalent of ray: src/ray/gcs/gcs_server/gcs_placement_group_manager.h:230.
    Because all scheduling is GCS-centric here, "prepare/commit 2-phase
    protocol across raylets" (gcs_placement_group_scheduler.cc) collapses
    to an atomic in-memory reservation: bundle resources move from the
    node's pool into the PG at creation, and leases inside the PG draw
    from the bundle instead of the node.
    """

    pg_id: PlacementGroupID
    name: Optional[str]
    strategy: str
    bundles: List[ResourceSet]
    state: str
    owner_job: Optional[JobID]
    detached: bool
    bundle_nodes: List[Optional[NodeID]]
    bundle_available: List[ResourceSet]
    namespace: str = "default"
    created_at: float = field(default_factory=time.time)


@dataclass
class ActorEntry:
    actor_id: ActorID
    name: Optional[str]
    namespace: str
    state: str
    owner_job: JobID
    max_restarts: int
    restarts_used: int = 0
    creation_spec: Any = None  # serialized class+args, kept for restarts
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling: Dict[str, Any] = field(default_factory=dict)
    worker_addr: Optional[str] = None
    node_id: Optional[NodeID] = None
    lease_id: Optional[int] = None
    detached: bool = False
    runtime_env: Optional[dict] = None  # descriptor for restart replay
    # graceful-drain policy: "migrate" (default — the GCS checkpoint/
    # restart-migrates it off a draining node) or "ignore" (an app-level
    # manager owns relocation, e.g. serve replicas ride the controller's
    # drain-then-stop flow instead)
    on_drain: str = "migrate"
    death_cause: Optional[str] = None
    num_pending_restart_waiters: int = 0
    # conn of the creating client while PENDING_CREATION; a PENDING actor
    # whose creator vanishes can never be reported started — kill it so
    # callers waiting on the state don't hang forever
    creator_conn: Any = None


@dataclass
class PendingLease:
    """A queued lease request waiting for capacity."""

    fut: asyncio.Future
    demand: ResourceSet
    strategy: Dict[str, Any]
    client_conn: rpc.Connection
    actor_id: Optional[ActorID]
    enqueued_at: float = field(default_factory=time.monotonic)
    # client-chosen tag (scheduling-class id) so the client can cancel
    # parked requests whose demand evaporated (ray: CancelWorkerLease)
    tag: Optional[int] = None


# --------------------------------------------------------------------------
# Scheduler policies (ray: raylet/scheduling/policy/* redesigned global)
# --------------------------------------------------------------------------


_NBUCKETS = 64          # utilization buckets (~1.6% granularity)
_FULL_BUCKET = _NBUCKETS        # max-utilization >= 1.0
_SUSPECT_BUCKET = _NBUCKETS + 1  # alive but failure-suspected: scanned
#   LAST by every strategy, so a suspect node costs placement
#   preference (nothing new lands there while healthy capacity exists)
#   without costing an outage — the DRAINING parking machinery, one
#   notch softer
_PARKED_BUCKET = _NBUCKETS + 2  # dead / draining / not-yet-attached


class Scheduler:
    """Global resource accounting + node selection.

    Scale: nodes live in a write-through utilization-bucket index
    (NodeEntry.__setattr__ re-buckets on every availability/liveness
    change), so node selection is O(1) amortized instead of an O(nodes)
    scan — the binpack/spread orderings become bucket-granular (~1.6%)
    approximations of their exact forms.  Feasibility checks are cached
    per demand signature (totals only change on membership changes).
    `_kick_pending` wakes queued requests through a bounded scan window,
    so a deep backlog (100k+ queued, reference envelope: 1M) costs
    O(granted + window) per freed lease, not O(backlog).
    """

    def __init__(self, gcs: "GcsServer"):
        self.gcs = gcs
        self.pending: Deque[PendingLease] = deque()
        self._buckets: List[Dict[NodeID, NodeEntry]] = [
            {} for _ in range(_PARKED_BUCKET + 1)
        ]
        self._node_entry: Dict[NodeID, NodeEntry] = {}  # indexed entry
        self._feasible_cache: Dict[tuple, bool] = {}
        # no-fit fast path: when nothing in the cluster fits a demand,
        # every queued waiter re-asks constantly (kick scans) — a full
        # fail scan touches the whole "full" bucket, O(nodes).  A no-fit
        # verdict stays valid until capacity INCREASES somewhere, so it's
        # cached against an epoch bumped on every availability increase
        # (returns, node joins, unparks) — never on debits, which can't
        # turn no-fit into fit.
        self._capacity_epoch = 0
        self._nofit: Dict[tuple, int] = {}

    # -- index maintenance ----------------------------------------------
    def index_node(self, n: NodeEntry):
        # Evict a superseded entry for the same node (raylet
        # re-registration builds a fresh NodeEntry): the old one may sit
        # in a different bucket and would otherwise remain pickable
        # forever — a live ghost the scheduler grants against.
        old = self._node_entry.get(n.node_id)
        if old is not None and old is not n:
            if old._bucket is not None:
                self._buckets[old._bucket].pop(n.node_id, None)
            object.__setattr__(old, "_sched", None)
            object.__setattr__(old, "_bucket", None)
        self._node_entry[n.node_id] = n
        object.__setattr__(n, "_sched", self)
        object.__setattr__(n, "_bucket", None)
        self.rebucket(n)
        self._feasible_cache.clear()

    def _bucket_of(self, n: NodeEntry) -> int:
        if not n.alive or n.conn is None or n.draining:
            return _PARKED_BUCKET
        if n.suspect:
            return _SUSPECT_BUCKET
        u = n.resources_available.utilization(n.resources_total)
        if u >= 1.0:
            return _FULL_BUCKET
        return min(int(u * _NBUCKETS), _NBUCKETS - 1)

    def rebucket(self, n: NodeEntry):
        b = self._bucket_of(n)
        old = n._bucket
        if b == old:
            return
        if old is not None:
            self._buckets[old].pop(n.node_id, None)
        self._buckets[b][n.node_id] = n
        object.__setattr__(n, "_bucket", b)
        if old is None or b < old:
            # capacity appeared (node joined / unparked / freed into a
            # lower-utilization bucket)
            self._capacity_epoch += 1
        if b == _PARKED_BUCKET or old == _PARKED_BUCKET:
            # liveness changed: cached feasibility may now be wrong
            self._feasible_cache.clear()

    def note_available_change(self, n: NodeEntry, old_rs, new_rs):
        """resources_available was assigned: rebucket, and bump the
        capacity epoch on any per-resource INCREASE even when the bucket
        index doesn't move (a 1-CPU return on a large node stays in the
        same ~1.6% bucket but can turn a cached no-fit into a fit)."""
        self.rebucket(n)
        if old_rs is None:
            self._capacity_epoch += 1
            return
        old_fp = old_rs._fp
        for k, v in new_rs._fp.items():
            if v > old_fp.get(k, 0):
                self._capacity_epoch += 1
                return

    # -- queries ---------------------------------------------------------
    def is_feasible(self, demand: ResourceSet) -> bool:
        key = tuple(sorted(demand._fp.items()))
        hit = self._feasible_cache.get(key)
        if hit is None:
            hit = any(
                n.alive and n.resources_total.covers(demand)
                for n in self.gcs.nodes.values()
            )
            self._feasible_cache[key] = hit
        return hit

    def pick_node(
        self, demand: ResourceSet, strategy: Dict[str, Any]
    ) -> Optional[NodeEntry]:
        """Returns a node with available capacity, or None (queue it)."""
        stype = strategy.get("type", "default")
        if stype == "node_affinity":
            node = self.gcs.nodes.get(NodeID.from_hex(strategy["node_id"]))
            if (node and node.alive and node.conn is not None
                    and not node.draining
                    and node.resources_available.covers(demand)):
                return node
            if node and strategy.get("soft", False):
                pass  # fall through to default placement
            elif node:
                return None  # hard affinity: wait for that node
            # unknown node id with hard affinity -> handled by caller
        # no-fit fast path (default/spread only — node_affinity restricts
        # the candidate set and is a cheap single lookup anyway)
        key = tuple(sorted(demand._fp.items()))
        if self._nofit.get(key) == self._capacity_epoch:
            return None
        if stype == "spread":
            # least-utilized first (bucket-granular); the "full" bucket
            # still gets scanned last — a node can be max-utilized in one
            # resource yet cover a demand on another; SUSPECT nodes are
            # the last resort in every strategy (alive, but failure-
            # suspected: new work prefers healthy capacity)
            node = self._first_covering(demand, range(0, _FULL_BUCKET + 1))
            if node is None:
                node = self._first_covering(demand, (_SUSPECT_BUCKET,))
            if node is None:
                self._note_nofit(key)
            return node
        # default: hybrid binpack — prefer the most-utilized node that
        # still fits while below the spread threshold, so small tasks pack
        # and big clusters don't fragment (ray: hybrid_scheduling_policy.cc
        # in spirit); above-threshold nodes next, max-utilized, then
        # suspect nodes last
        thresh_b = min(
            int(cfg.sched_spread_threshold * _NBUCKETS), _NBUCKETS
        )
        node = self._first_covering(demand, range(thresh_b - 1, -1, -1))
        if node is None:
            node = self._first_covering(
                demand, range(_NBUCKETS - 1, thresh_b - 1, -1)
            )
        if node is None:
            node = self._first_covering(
                demand, (_FULL_BUCKET, _SUSPECT_BUCKET)
            )
        if node is None:
            self._note_nofit(key)
        return node

    def _note_nofit(self, key):
        if len(self._nofit) > 4096:
            self._nofit.clear()
        self._nofit[key] = self._capacity_epoch

    def _first_covering(self, demand, bucket_order):
        for b in bucket_order:
            for n in self._buckets[b].values():
                if n.resources_available.covers(demand):
                    return n
        return None


# --------------------------------------------------------------------------
# GCS server
# --------------------------------------------------------------------------


class CheckpointStore:
    """Debounced snapshot persistence for GCS fault tolerance.

    Role-equivalent of the reference's Redis/observability-backed
    StoreClient (ray: src/ray/gcs/store_client/store_client.h,
    redis_store_client.h): GCS tables are flushed to one pickle file
    (atomic tmp+rename) shortly after every mutation, and reloaded on
    restart so the cluster can re-attach instead of dying with the head.
    A single local file instead of Redis is deliberate: TPU pods mount a
    shared or local session dir, and the write set (control-plane tables,
    not objects) is small.
    """

    def __init__(self, path: str):
        self.path = path
        self._dirty = False
        self._flush_task: Optional[asyncio.Task] = None
        self._get_state: Optional[Any] = None  # set by the server
        self._wal_path = path + ".wal"
        self._wal_file = None

    def load(self) -> Optional[dict]:
        import pickle

        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception:
            logger.exception("GCS checkpoint at %s unreadable; starting fresh",
                             self.path)
            return None

    def load_wal(self) -> list:
        """Records appended after the last snapshot, oldest first.  A torn
        final record (crash mid-append) ends the replay cleanly."""
        import pickle

        records = []
        try:
            with open(self._wal_path, "rb") as f:
                while True:
                    records.append(pickle.load(f))
        except FileNotFoundError:
            pass
        except Exception:
            pass  # EOF or torn tail — replay what we have
        return records

    def wal_append(self, record) -> None:
        """O(delta) durability for critical mutations: append one pickled
        record and flush to the OS (process-crash durable, like the
        reference's Redis write-before-ack) instead of rewriting the full
        snapshot inline with the RPC reply."""
        import pickle

        try:
            if self._wal_file is None:
                self._wal_file = open(self._wal_path, "ab")
            pickle.dump(record, self._wal_file, protocol=5)
            self._wal_file.flush()
        except Exception:
            logger.exception("GCS WAL append failed")

    def mark_dirty(self):
        self._dirty = True
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._flush_soon()
            )

    async def _flush_soon(self):
        await asyncio.sleep(cfg.gcs_checkpoint_debounce_s)
        self.flush()

    def flush(self):
        import os
        import pickle

        if not self._dirty or self._get_state is None:
            return
        self._dirty = False
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(self._get_state(), f, protocol=5)
            os.replace(tmp, self.path)
        except Exception:
            logger.exception("GCS checkpoint flush failed")
            return
        # the snapshot now covers everything the WAL recorded
        if self._wal_file is not None:
            try:
                self._wal_file.truncate(0)
                self._wal_file.seek(0)
            except Exception:
                logger.exception("GCS WAL truncate failed")
        else:
            try:
                os.unlink(self._wal_path)
            except FileNotFoundError:
                pass
            except Exception:
                pass


#: rpc methods that only mutate the high-churn object tables; their
#: checkpoint rides a separate, debounce-only file so critical control
#: flushes stay O(control-plane state)
_OBJECT_RPCS = frozenset({
    "add_object_location", "remove_object_location", "free_objects",
    "ref_edge", "ref_update", "add_spilled_location",
    "object_notify_batch",
})

#: rpc methods whose effects must survive an immediate crash: flushed
#: synchronously before the reply (the reference writes Redis before
#: acking — gcs_actor_manager.cc persistence-first pattern).  High-churn
#: mutations (object locations, refcounts) stay on the debounced path.
_CRITICAL_RPCS = frozenset({
    "register_actor", "actor_started", "actor_creation_failed",
    "kill_actor", "create_placement_group", "remove_placement_group",
    "register_node", "register_job", "kv_put", "kv_del",
})

#: rpc methods that never mutate durable GCS state (no checkpoint after
#: these; metrics are ephemeral by design)
_READONLY_RPCS = frozenset({
    "get_nodes", "cluster_resources", "kv_get", "kv_exists", "kv_keys",
    "get_object_locations", "get_actor", "list_actors", "heartbeat",
    "get_placement_group", "list_placement_groups",
    "wait_placement_group_ready", "ping", "subscribe", "unsubscribe",
    "get_drain_status",
    "get_autoscaler_state", "list_tasks", "list_objects",
    "metrics_push", "get_metrics", "get_job_info", "get_job_logs",
    "list_jobs", "list_events", "report_event", "get_worker_death_info",
    "cluster_store_stats", "dump_worker_stacks", "cancel_lease_requests",
    "dump_tasks", "publish", "chaos_partition", "chaos_heal",
    "node_health",
})


class GcsServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        session_dir: Optional[str] = None,
    ):
        self.server = rpc.Server(
            self._handle, host=host, port=port, on_close=self._conn_closed
        )
        self.checkpoint: Optional[CheckpointStore] = None
        self.checkpoint_objects: Optional[CheckpointStore] = None
        if session_dir:
            import os

            os.makedirs(session_dir, exist_ok=True)
            self.checkpoint = CheckpointStore(
                os.path.join(session_dir, "gcs_checkpoint.pkl")
            )
            self.checkpoint._get_state = self._snapshot_state
            self.checkpoint_objects = CheckpointStore(
                os.path.join(session_dir, "gcs_objects.pkl")
            )
            self.checkpoint_objects._get_state = self._snapshot_object_state
        self.nodes: Dict[NodeID, NodeEntry] = {}
        # health plane: per-node phi-accrual detectors (alive, attached
        # nodes only) and the monotonic incarnation counters (persisted
        # — fencing must survive a GCS restart, or a zombie could
        # re-enter through the reborn control plane)
        self.node_health: Dict[NodeID, PhiAccrualDetector] = {}
        self.node_incarnations: Dict[NodeID, int] = {}
        self.actors: Dict[ActorID, ActorEntry] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}  # (ns, name)
        self.jobs: Dict[JobID, dict] = {}
        self.kv: Dict[str, bytes] = {}
        self.leases: Dict[int, LeaseEntry] = {}
        self._lease_ids = iter(range(1, 1 << 62))
        self.scheduler = Scheduler(self)
        # placement groups
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupEntry] = {}
        self.named_pgs: Dict[Tuple[str, str], PlacementGroupID] = {}
        self._pending_pgs: List[PlacementGroupID] = []
        self._pg_state_waiters: Dict[PlacementGroupID, List[asyncio.Future]] = {}
        # object directory: object_id bytes -> {node_id}
        self.object_locations: Dict[bytes, Set[NodeID]] = {}
        self.object_sizes: Dict[bytes, int] = {}
        # objects spilled to a node's disk (the file outlives the arena
        # copy; reference role: object directory's spilled-URL field,
        # gcs_object_manager + local_object_manager.h:110)
        self.spilled_objects: Dict[bytes, NodeID] = {}
        # recently freed oids: a location announce racing the free (a
        # restore or pull finishing after delete_objects went out) must
        # not resurrect the object's directory entry.  Object ids are
        # never reused, so a bounded FIFO window is sufficient.
        self._freed_tombstones: "OrderedDict[bytes, None]" = OrderedDict()
        self._location_waiters: Dict[bytes, List[asyncio.Future]] = {}
        # distributed refcounting: object_id -> holder tokens (worker_id
        # bytes for processes, b"actor:<id>" for actor creation specs).
        # When a registered object's holder set empties, the object is
        # freed cluster-wide after a short grace (reference analogue: the
        # owner releasing its ReferenceCounter entry, reference_count.h:61)
        self.object_holders: Dict[bytes, Set[bytes]] = {}
        self.object_edges: Dict[bytes, List[bytes]] = {}  # parent -> children
        self._free_scheduled: Set[bytes] = set()
        # pubsub: channel -> set of conns
        self.subscribers: Dict[str, Set[rpc.Connection]] = {}
        # conn bookkeeping
        self._conn_leases: Dict[rpc.Connection, Set[int]] = {}
        self._conn_node: Dict[rpc.Connection, NodeID] = {}
        self._conn_job: Dict[rpc.Connection, JobID] = {}
        self._worker_conns: Dict[WorkerID, rpc.Connection] = {}
        self._worker_death_reasons: Dict[bytes, str] = {}
        # in-flight graceful drains: node_id -> asyncio.Task (strong refs;
        # the loop holds tasks weakly and a GC'd drain would silently stop)
        self._drain_tasks: Dict[NodeID, asyncio.Task] = {}
        # shielded drain-migration actor restarts (strong refs only: a
        # drain-deadline cancel orphans the shield inner, which must
        # keep running onto its surviving node)
        self._restart_tasks: Set[asyncio.Task] = set()
        self._events: List[dict] = []  # bounded structured event log
        self._health_task: Optional[asyncio.Task] = None
        self._start_time = time.time()
        # observability: reporter id -> latest metric snapshot
        self.metrics_by_reporter: Dict[str, dict] = {}
        # submitted driver jobs (job_submission.py): sub_id -> info
        self.submitted_jobs: Dict[str, dict] = {}
        self.session_dir = session_dir

    # ---- persistence ---------------------------------------------------
    def _mark_dirty(self):
        if self.checkpoint is not None:
            self.checkpoint.mark_dirty()

    def _mark_objects_dirty(self):
        if self.checkpoint_objects is not None:
            self.checkpoint_objects.mark_dirty()

    def _snapshot_state(self) -> dict:
        """Connection-free copy of every durable table."""
        actors = {}
        for aid, a in self.actors.items():
            c = copy.copy(a)
            c.creator_conn = None
            actors[aid] = c
        nodes = {
            nid: {
                "address": n.address,
                "resources": n.resources_total.to_dict(),
                "labels": n.labels,
                "incarnation": n.incarnation,
                # a restart must not silently re-admit a node the
                # provider is mid-way through terminating
                "draining": n.draining,
                "drain_reason": n.drain_reason,
                "drain_status": dict(n.drain_status)
                if n.drain_status else None,
            }
            for nid, n in self.nodes.items()
            if n.alive
        }
        return {
            "version": 1,
            "nodes": nodes,
            "node_incarnations": dict(self.node_incarnations),
            "actors": actors,
            "named_actors": dict(self.named_actors),
            "jobs": {j: dict(v) for j, v in self.jobs.items()},
            "kv": dict(self.kv),
            "placement_groups": {
                pid: copy.copy(pg) for pid, pg in self.placement_groups.items()
            },
            "named_pgs": dict(self.named_pgs),
            "submitted_jobs": {
                k: {kk: vv for kk, vv in v.items() if not kk.startswith("_")}
                for k, v in self.submitted_jobs.items()
            },
        }

    def _snapshot_object_state(self) -> dict:
        return {
            "object_locations": {
                k: set(v) for k, v in self.object_locations.items()
            },
            "object_sizes": dict(self.object_sizes),
            "object_holders": {
                k: set(v) for k, v in self.object_holders.items()
            },
            "object_edges": {k: list(v) for k, v in self.object_edges.items()},
            "spilled_objects": dict(self.spilled_objects),
        }

    def _restore_object_state(self, st: dict):
        self.object_locations.update(st["object_locations"])
        self.object_sizes.update(st["object_sizes"])
        self.object_holders.update(st["object_holders"])
        self.object_edges.update(st["object_edges"])
        self.spilled_objects.update(st.get("spilled_objects", {}))

    def _restore_state(self, st: dict):
        """Rebuild tables from a snapshot; connections re-attach lazily.

        Nodes come back as alive-with-no-conn entries: their raylets hold
        ReconnectingConnections and will re-register within the death
        timeout, re-applying placement-group bundle debits; ones that
        don't are reaped by the normal health loop.  ALIVE actors keep
        serving the whole time — actor calls ride direct client->worker
        connections that never touched the GCS.
        """
        now = time.monotonic()
        self.node_incarnations.update(st.get("node_incarnations", {}))
        for nid, n in st["nodes"].items():
            self.nodes[nid] = entry = NodeEntry(
                node_id=nid,
                address=n["address"],
                resources_total=ResourceSet(n["resources"]),
                resources_available=ResourceSet(n["resources"]),
                labels=n["labels"],
                conn=None,
                alive=True,
                incarnation=n.get("incarnation", 0),
                last_heartbeat=now,
            )
            if n.get("draining"):
                entry.drain_reason = n.get("drain_reason")
                entry.drain_status = n.get("drain_status")
                if entry.drain_status and entry.drain_status.get(
                    "state"
                ) == "draining":
                    # the drain task died with the old GCS: report it
                    # settled-as-failed (pollers must not wait forever)
                    # but keep the node excluded — the provider's kill
                    # is still coming and the hard-death path cleans up
                    entry.drain_status["state"] = "failed"
                    entry.drain_status["error"] = "GCS restarted mid-drain"
                entry.draining = True
            self.scheduler.index_node(entry)
        self.actors.update(st["actors"])
        self.named_actors.update(st["named_actors"])
        self.jobs.update(st["jobs"])
        self.kv.update(st["kv"])
        self.placement_groups.update(st["placement_groups"])
        self.named_pgs.update(st["named_pgs"])
        for k, v in st.get("submitted_jobs", {}).items():
            # a restart orphans the driver subprocess handle; a job still
            # marked RUNNING has unknown fate — report FAILED conservatively
            if v.get("status") == RUNNING_JOB:
                v = dict(v, status=FAILED_JOB,
                         end_time=v.get("end_time") or time.time())
            self.submitted_jobs[k] = v
        # A PENDING actor's creating client must re-drive creation itself
        # (its conn died with us); mid-restart actors get their restart
        # replayed once nodes have had a chance to re-register.  Leases
        # are NOT checkpointed and the lease-id counter restarts, so any
        # restored lease_id is stale — scrub it (a fresh synthetic lease
        # is attached when the hosting raylet re-registers).
        to_replay = []
        for a in self.actors.values():
            a.lease_id = None
            if a.state == ACTOR_PENDING:
                a.state = ACTOR_DEAD
                a.death_cause = "GCS restarted during creation"
            elif a.state == ACTOR_RESTARTING:
                to_replay.append(a)
        # Re-derive per-node available resources: nothing holds leases
        # across a restart, but CREATED placement groups keep their
        # bundle reservations (re-debited in rpc_register_node).
        for pg in self.placement_groups.values():
            if pg.state == PG_PENDING:
                self._pending_pgs.append(pg.pg_id)
        if to_replay:
            async def _replay():
                await asyncio.sleep(cfg.node_death_timeout_s)
                for a in to_replay:
                    if a.state == ACTOR_RESTARTING:
                        await self._restart_actor(a, "GCS restart replay")

            # keep a strong ref: the loop holds tasks weakly and a
            # GC'd task would silently drop the replay
            self._replay_task = asyncio.get_running_loop().create_task(
                _replay()
            )
        logger.info(
            "GCS state restored: %d nodes, %d actors, %d PGs, %d kv keys",
            len(self.nodes), len(self.actors),
            len(self.placement_groups), len(self.kv),
        )

    # ---- lifecycle -----------------------------------------------------
    async def start(self):
        if self.checkpoint is not None:
            st = self.checkpoint.load()
            wal = self.checkpoint.load_wal()
            if wal:
                if not st:
                    st = {
                        "version": 1, "nodes": {}, "actors": {},
                        "named_actors": {}, "jobs": {}, "kv": {},
                        "placement_groups": {}, "named_pgs": {},
                        "submitted_jobs": {},
                    }
                st = self._apply_wal(st, wal)
            if st:
                self._restore_state(st)
            if wal:
                # Compact immediately: a torn tail from the crash would
                # otherwise stay in the file, and records appended after
                # it would be unreachable by the next replay (load_wal
                # stops at the first bad record).
                self.checkpoint._dirty = True
                self.checkpoint.flush()
            ost = self.checkpoint_objects.load()
            if ost:
                self._restore_object_state(ost)
        await self.server.start()
        self._health_task = asyncio.get_running_loop().create_task(
            self._health_loop()
        )
        logger.info("GCS listening on %s", self.server.address)

    async def close(self):
        if self._health_task:
            self._health_task.cancel()
        if self.checkpoint is not None:
            self.checkpoint.flush()
        if self.checkpoint_objects is not None:
            self.checkpoint_objects.flush()
        await self.server.close()

    @property
    def address(self) -> str:
        return self.server.address

    # ---- dispatch ------------------------------------------------------
    async def _handle(self, conn: rpc.Connection, method: str, p: Any):
        fn = getattr(self, f"rpc_{method}", None)
        if fn is None:
            raise rpc.RpcError(f"GCS: unknown method {method!r}")
        result = await fn(conn, p)
        if method in _OBJECT_RPCS:
            if self.checkpoint_objects is not None:
                self.checkpoint_objects.mark_dirty()
        elif method not in _READONLY_RPCS:
            self._mark_dirty()
            if method in _CRITICAL_RPCS and self.checkpoint is not None:
                # O(delta) persistence before the ack: append just the
                # mutated rows to the WAL; the debounced snapshot
                # (cfg.gcs_checkpoint_debounce_s) compacts it.  Rewriting
                # the full snapshot inline here capped PG churn at ~150/s.
                for rec in self._wal_records(method, p):
                    self.checkpoint.wal_append(rec)
        return result

    def _wal_records(self, method: str, p: Any) -> list:
        """Snapshot-representation deltas for a critical mutation, applied
        over the loaded snapshot at restore (see start()).  Covers the
        primary row the ack promises durability for; cascaded effects on
        other tables ride the debounced snapshot like everything else."""
        recs = []
        if method in ("create_placement_group", "remove_placement_group"):
            pid = PlacementGroupID(p["pg_id"])
            pg = self.placement_groups.get(pid)
            if pg is not None:
                recs.append(("put", "placement_groups", pid, copy.copy(pg)))
                if pg.name:
                    key = (pg.namespace, pg.name)
                    if self.named_pgs.get(key) == pid:
                        recs.append(("put", "named_pgs", key, pid))
                    else:
                        recs.append(("del", "named_pgs", key))
        elif method in ("register_actor", "actor_started",
                        "actor_creation_failed", "kill_actor"):
            aid = ActorID(p["actor_id"])
            actor = self.actors.get(aid)
            if actor is not None:
                c = copy.copy(actor)
                c.creator_conn = None
                recs.append(("put", "actors", aid, c))
                if actor.name:
                    key = (actor.namespace, actor.name)
                    if self.named_actors.get(key) == aid:
                        recs.append(("put", "named_actors", key, aid))
                    else:
                        recs.append(("del", "named_actors", key))
        elif method == "register_node":
            nid = NodeID(p["node_id"])
            n = self.nodes.get(nid)
            if n is not None and n.alive:
                recs.append(("put", "nodes", nid, {
                    "address": n.address,
                    "resources": n.resources_total.to_dict(),
                    "labels": n.labels,
                    "incarnation": n.incarnation,
                }))
                # the fencing token must be crash-durable with the ack:
                # a restarted GCS re-admitting a zombie at its old
                # incarnation would re-open the split-brain window
                recs.append((
                    "put", "node_incarnations", nid,
                    self.node_incarnations.get(nid, n.incarnation),
                ))
        elif method == "register_job":
            # a fresh registration has no job_id in the payload (the GCS
            # generates one); its row rides the debounced snapshot and the
            # driver re-registers on reconnect anyway
            if p.get("job_id"):
                jid = JobID(p["job_id"])
                j = self.jobs.get(jid)
                if j is not None:
                    recs.append(("put", "jobs", jid, dict(j)))
        elif method == "kv_put":
            recs.append(("put", "kv", p["key"], self.kv.get(p["key"])))
        elif method == "kv_del":
            recs.append(("del", "kv", p["key"]))
        return recs

    @staticmethod
    def _apply_wal(snap: dict, records: list) -> dict:
        for rec in records:
            try:
                if rec[0] == "put":
                    _, table, key, value = rec
                    snap.setdefault(table, {})[key] = value
                elif rec[0] == "del":
                    _, table, key = rec
                    snap.setdefault(table, {}).pop(key, None)
            except Exception:
                logger.exception("bad WAL record skipped: %r", rec[:2])
        return snap

    def _conn_closed(self, conn: rpc.Connection):
        loop = asyncio.get_event_loop()
        loop.create_task(self._cleanup_conn(conn))

    async def _cleanup_conn(self, conn: rpc.Connection):
        # Release leases held by a disconnected submitter.  kick=False +
        # one kick at the end: a dead driver can hold tens of thousands
        # of leases (scale tests hold 32k), and a kick per release is
        # O(leases × kick) of synchronous event-loop work that starves
        # every other RPC for minutes.
        held = list(self._conn_leases.pop(conn, ()))
        for lease_id in held:
            await self._release_lease(lease_id, kick=False)
        if held:
            self._kick_pending()
        # node connection lost -> node death, unless the raylet already
        # re-registered over a NEWER connection (half-open TCP: the stale
        # server-side socket can outlive the replacement)
        node_id = self._conn_node.pop(conn, None)
        if node_id is not None:
            node = self.nodes.get(node_id)
            if node is None or node.conn is conn or node.conn is None:
                await self._on_node_death(node_id, "raylet connection lost")
        job_id = self._conn_job.pop(conn, None)
        if job_id is not None and job_id not in self._conn_job.values():
            await self._on_job_finished(job_id)
        # orphaned creations: a PENDING actor whose creating client is gone
        # will never receive actor_started — fail it now
        for actor in list(self.actors.values()):
            if actor.state == ACTOR_PENDING and actor.creator_conn is conn:
                await self._kill_actor(
                    actor, "creating client disconnected", no_restart=True
                )
        for wid, c in list(self._worker_conns.items()):
            if c is conn:
                del self._worker_conns[wid]
                self._scrub_holder(wid.binary())
        for subs in self.subscribers.values():
            subs.discard(conn)

    # ---- health --------------------------------------------------------
    #
    # Adaptive failure detection (reference role: GcsHealthCheckManager,
    # gcs_health_check_manager.h, upgraded from fixed-timeout to
    # phi-accrual — common/health.py).  Verdicts per alive node:
    #
    #   phi >= health_phi_suspect  -> SUSPECT: parked in the scheduler's
    #       last-resort bucket, deprioritized for pulls and serve
    #       routing; NOTHING killed/reformed/restarted.  Cleared by the
    #       next heartbeat.
    #   phi >= health_phi_death AND silence >= floor -> confirmed DEAD
    #       (floor = health_death_floor_frac x node_death_timeout_s: a
    #       whole-process stall must not mass-kill fast-heartbeat nodes)
    #   silence > node_death_timeout_s -> DEAD regardless of phi (hard
    #       cap: adaptive detection never detects SLOWER than the old
    #       fixed detector)
    #
    # Nodes without enough history (or restored without a conn) keep the
    # fixed-timeout behavior.
    async def _health_loop(self):
        while True:
            await asyncio.sleep(cfg.heartbeat_interval_s)
            # reap finished driver subprocesses even when nobody polls
            # (zombies otherwise; and the checkpoint must not persist a
            # finished job as RUNNING)
            try:
                self._poll_submitted_jobs()
            except Exception:
                pass
            now = time.monotonic()
            death_floor = (
                cfg.node_death_timeout_s * cfg.health_death_floor_frac
            )
            for node in list(self.nodes.values()):
                if not node.alive:
                    continue
                elapsed = now - node.last_heartbeat
                det = self.node_health.get(node.node_id)
                if det is None or not det.ready() or node.conn is None:
                    if elapsed > cfg.node_death_timeout_s:
                        await self._on_node_death(
                            node.node_id, "heartbeat timeout"
                        )
                    continue
                phi = det.phi(now)
                if death_confirmed(phi, elapsed, cfg.health_phi_death,
                                   death_floor, cfg.node_death_timeout_s):
                    await self._on_node_death(
                        node.node_id,
                        f"failure detector confirmed death "
                        f"(phi={phi:.1f}, silent {elapsed:.2f}s)",
                    )
                elif is_suspect(phi, cfg.health_phi_suspect) and not node.suspect:
                    node.suspect = True  # re-buckets to last-resort
                    self.record_cluster_event(
                        "WARNING", "gcs",
                        f"node suspected (phi={phi:.1f}, silent "
                        f"{elapsed:.2f}s): deprioritized, not killed",
                        node_id=node.node_id.hex(),
                    )
                    await self.publish("nodes", {
                        "event": "suspect",
                        "node_id": node.node_id.hex(),
                        "incarnation": node.incarnation,
                        "phi": phi,
                    })
            # Compact cancelled/abandoned pending-lease entries: kicks
            # drop them lazily, but kicks are event-driven — a saturated
            # cluster with clients re-requesting on LEASE_PENDING every
            # 60 s would otherwise accumulate dead entries without bound.
            pending = self.scheduler.pending
            if any(e.fut.done() or e.client_conn.closed for e in pending):
                keep: deque = deque()
                for e in pending:
                    if e.fut.done():
                        continue
                    if e.client_conn.closed:
                        e.fut.cancel()
                        continue
                    keep.append(e)
                self.scheduler.pending = keep

    async def _on_node_death(self, node_id: NodeID, reason: str):
        self._mark_dirty()
        node = self.nodes.get(node_id)
        if not node or not node.alive:
            return
        node.alive = False
        node.suspect = False  # parked now; suspicion is moot
        # fence the dead life: bump the incarnation counter PAST the
        # node's, so every RPC the old life may still send (a healed
        # partition, a zombie raylet) is rejected with FencedError
        self.node_incarnations[node_id] = max(
            self.node_incarnations.get(node_id, 0), node.incarnation
        ) + 1
        self.node_health.pop(node_id, None)
        if self.checkpoint is not None:
            self.checkpoint.flush()
        # a drain in flight for this node is moot now (the failure path
        # pops itself before calling here, so this never self-cancels)
        drain_task = self._drain_tasks.pop(node_id, None)
        if drain_task is not None:
            drain_task.cancel()
        if node.drain_status is not None and node.drain_status.get(
            "state"
        ) == "draining":
            node.drain_status["state"] = "dead"
        logger.warning("node %s died: %s", node_id, reason)
        self.record_cluster_event(
            "ERROR", "gcs", f"node died: {reason}",
            node_id=node_id.hex(),
        )
        # drop object locations on that node
        for oid, locs in list(self.object_locations.items()):
            locs.discard(node_id)
            if not locs:
                del self.object_locations[oid]
        # break leases on that node — kick=False + one kick after: a
        # dense node (fractional-CPU actors) can hold thousands of
        # leases, and a kick per release is the same O(leases × kick)
        # event-loop starvation _cleanup_conn's batching eliminates
        broke = 0
        for lease_id, lease in list(self.leases.items()):
            if lease.node_id == node_id:
                await self._release_lease(lease_id, broken=True, kick=False)
                broke += 1
        if broke:
            self._kick_pending()
        # restart/kill actors that lived there
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (
                ACTOR_ALIVE,
                ACTOR_PENDING,
            ):
                await self._maybe_restart_actor(actor, f"node died: {reason}")
        # reschedule placement-group bundles that lived there
        for pg in list(self.placement_groups.values()):
            if pg.state not in (PG_CREATED, PG_RESCHEDULING):
                continue
            lost = [
                i for i, nid in enumerate(pg.bundle_nodes) if nid == node_id
            ]
            if not lost:
                continue
            for i in lost:
                pg.bundle_nodes[i] = None
                pg.bundle_available[i] = ResourceSet()
            pg.state = PG_RESCHEDULING
            if pg.pg_id not in self._pending_pgs:
                self._pending_pgs.append(pg.pg_id)
            await self.publish(
                "placement_groups",
                {"event": "rescheduling", "pg_id": pg.pg_id.hex()},
            )
        await self.publish("nodes", {
            "event": "dead",
            "node_id": node_id.hex(),
            # the NEW (fenced-to) incarnation: peers raise their
            # watermark past the dead life's token
            "incarnation": self.node_incarnations[node_id],
        })
        self._kick_pending()

    async def _on_job_finished(self, job_id: JobID):
        self.jobs.get(job_id, {}).update(state="FINISHED")
        # kill non-detached actors owned by the job
        for actor in list(self.actors.values()):
            if actor.owner_job == job_id and not actor.detached:
                await self._kill_actor(actor, "owner job finished", no_restart=True)
        # remove non-detached placement groups owned by the job
        for pg in list(self.placement_groups.values()):
            if pg.owner_job == job_id and not pg.detached and pg.state != PG_REMOVED:
                await self._remove_pg(pg)
        await self.publish("jobs", {"event": "finished", "job_id": job_id.hex()})

    # ---- pubsub --------------------------------------------------------
    async def publish(self, channel: str, message: dict):
        for conn in list(self.subscribers.get(channel, ())):
            try:
                await conn.notify("publish", {"channel": channel, "message": message})
            except Exception:
                pass

    async def rpc_publish(self, conn, p):
        """Client-initiated publish (worker log streaming rides this;
        reference role: log_monitor -> GCS pubsub -> driver print_logs,
        python/ray/_private/log_monitor.py:103)."""
        await self.publish(p["channel"], p["message"])
        return True

    async def rpc_subscribe(self, conn, p):
        self.subscribers.setdefault(p["channel"], set()).add(conn)
        return True

    async def rpc_unsubscribe(self, conn, p):
        self.subscribers.get(p["channel"], set()).discard(conn)
        return True

    # ---- nodes ---------------------------------------------------------
    def _check_node_fence(self, node_id: NodeID, inc) -> None:
        """Reject an RPC carrying a stale node incarnation.  ``inc`` is
        the sender's claimed incarnation (None = legacy/fresh caller:
        no check).  The raised FencedError reaches the zombie raylet as
        a RemoteCallError and triggers its self-fence (kill workers,
        discard object copies, re-register fresh)."""
        if inc is None:
            return
        cur = self.node_incarnations.get(node_id, 0)
        if inc < cur:
            raise FencedError(
                f"node {node_id.hex()[:12]} incarnation {inc} is stale "
                f"(current {cur}): the node was declared dead — fence "
                f"yourself (kill workers, discard objects) and "
                f"re-register fresh"
            )

    async def rpc_register_node(self, conn, p):
        node_id = NodeID(p["node_id"])
        # incarnation assignment: a fresh registration (no claimed
        # incarnation) always starts a NEW life; a reconnect claiming
        # the CURRENT incarnation keeps its life (transient conn loss /
        # GCS restart — its object copies and leases are still valid);
        # a stale claim is fenced — the raylet must purge before
        # re-joining (closing the healed-partition split brain)
        prev_inc = p.get("incarnation")
        cur = self.node_incarnations.get(node_id, 0)
        prev_entry = self.nodes.get(node_id)
        if prev_inc is not None:
            self._check_node_fence(node_id, prev_inc)
            if prev_entry is not None and not prev_entry.alive:
                # counter bump lost (pre-fencing snapshot): still treat
                # a re-registration from a declared-dead life as fenced
                raise FencedError(
                    f"node {node_id.hex()[:12]} was declared dead; "
                    f"purge and re-register fresh"
                )
            inc = max(prev_inc, cur)
        else:
            inc = cur + 1
        self.node_incarnations[node_id] = inc
        entry = NodeEntry(
            node_id=node_id,
            address=p["address"],
            resources_total=ResourceSet(p["resources"]),
            resources_available=ResourceSet(p["resources"]),
            labels=p.get("labels", {}),
            conn=conn,
            incarnation=inc,
        )
        # Re-registration (GCS restarted, raylet re-attaching): the fresh
        # available pool must re-absorb reservations that survive a
        # restart — CREATED/RESCHEDULING placement-group bundles placed on
        # this node, and the resources of restored ALIVE actors still
        # running here.  (Plain task leases die with the GCS; their
        # workers are reclaimed by the raylet's idle reaper.)
        for pg in self.placement_groups.values():
            if pg.state == PG_REMOVED:
                continue
            for bi, bnode in enumerate(pg.bundle_nodes):
                if bnode == node_id:
                    entry.resources_available = (
                        entry.resources_available.subtract(pg.bundles[bi])
                    )
        # transient reconnect (GCS never restarted): live leases on this
        # node are still tracked and their debits must carry over — bundle
        # draws (pg_ref) live inside bundle_available and must not debit
        # the node pool twice
        for lease in self.leases.values():
            if lease.node_id == node_id and lease.pg_ref is None:
                entry.resources_available = (
                    entry.resources_available.subtract(lease.resources)
                )
        for actor in self.actors.values():
            if (
                actor.state in (ACTOR_ALIVE, ACTOR_RESTARTING)
                and actor.node_id == node_id
                and actor.lease_id is None
            ):
                # synthesize the lease the old GCS held, so the actor's
                # capacity is debited now and refunded on its death
                lease_id = next(self._lease_ids)
                sched = actor.scheduling or {}
                pg_ref = None
                if sched.get("type") == "placement_group":
                    pgid = PlacementGroupID.from_hex(sched["pg_id"])
                    pg = self.placement_groups.get(pgid)
                    if pg is not None:
                        bi = sched.get("bundle_index", -1)
                        if bi is None or bi < 0:
                            bi = next(
                                (
                                    i
                                    for i, bn in enumerate(pg.bundle_nodes)
                                    if bn == node_id
                                ),
                                None,
                            )
                        if bi is not None:
                            pg_ref = (pgid, bi)
                res = ResourceSet(actor.resources)
                if pg_ref is None:
                    # bundle draws persisted inside bundle_available; only
                    # non-PG actors debit the node pool directly
                    entry.resources_available = (
                        entry.resources_available.subtract(res)
                    )
                self.leases[lease_id] = LeaseEntry(
                    lease_id=lease_id,
                    node_id=node_id,
                    worker_id=WorkerID.nil(),
                    worker_addr=actor.worker_addr or "",
                    resources=res,
                    client_conn=_GCS_SELF_CONN,
                    actor_id=actor.actor_id,
                    pg_ref=pg_ref,
                )
                actor.lease_id = lease_id
        # drop a stale conn mapping from a previous connection so its
        # eventual close is not mistaken for a node death
        for old_conn, nid in list(self._conn_node.items()):
            if nid == node_id and old_conn is not conn:
                del self._conn_node[old_conn]
        # a raylet reconnecting mid-drain must come back DRAINING: the
        # fresh entry would otherwise silently re-admit a node the
        # provider is about to terminate
        prev = self.nodes.get(node_id)
        if prev is not None and prev.draining:
            entry.drain_reason = prev.drain_reason
            entry.drain_status = prev.drain_status
            entry.draining = True
        self.nodes[node_id] = entry
        self.scheduler.index_node(entry)
        self._conn_node[conn] = node_id
        # label the conn for the partition plane + start a fresh
        # inter-heartbeat history (stale stats from the previous life
        # would poison the adaptive detector's first verdicts)
        conn.peer_endpoint = node_id.hex()
        self.node_health[node_id] = PhiAccrualDetector(
            window=cfg.health_window,
            min_std_frac=cfg.health_min_std_frac,
            min_samples=cfg.health_min_samples,
        )
        await self.publish(
            "nodes",
            {
                # a reconnecting mid-drain node must not announce "alive"
                # — subscribers (the serve controller's draining-node set)
                # would un-track it and route traffic back onto a node
                # the provider is about to terminate
                "event": "draining" if entry.draining else "alive",
                "node_id": node_id.hex(),
                "address": p["address"],
                "incarnation": inc,
            },
        )
        logger.info(
            "node %s registered: %s %s (incarnation %d)",
            node_id, p["address"], entry.resources_total, inc,
        )
        self._kick_pending()
        return {"gcs_time": time.time(), "incarnation": inc}

    async def rpc_heartbeat(self, conn, p):
        node_id = NodeID(p["node_id"])
        # fencing: a zombie's heartbeat is the rendezvous where it
        # LEARNS it was declared dead (the heal-side of a partition)
        self._check_node_fence(node_id, p.get("incarnation"))
        node = self.nodes.get(node_id)
        if node:
            now = time.monotonic()
            node.last_heartbeat = now
            det = self.node_health.get(node_id)
            if det is not None:
                det.heartbeat(now)
            if node.suspect:
                node.suspect = False  # un-parks in the scheduler index
                self.record_cluster_event(
                    "INFO", "gcs", "suspected node recovered",
                    node_id=node_id.hex(),
                )
                await self.publish("nodes", {
                    "event": "recovered",
                    "node_id": node_id.hex(),
                    "incarnation": node.incarnation,
                })
                self._kick_pending()
        return True

    async def rpc_get_nodes(self, conn, p):
        return [
            {
                "node_id": n.node_id.hex(),
                "address": n.address,
                # a restored-but-unattached node is not usable yet
                "alive": n.alive and n.conn is not None,
                "suspect": n.suspect,
                "incarnation": n.incarnation,
                "draining": n.draining,
                "resources_total": n.resources_total.to_dict(),
                "resources_available": n.resources_available.to_dict(),
                "labels": n.labels,
            }
            for n in self.nodes.values()
        ]

    async def rpc_cluster_resources(self, conn, p):
        total: ResourceSet = ResourceSet()
        avail: ResourceSet = ResourceSet()
        for n in self.nodes.values():
            if n.alive:
                total = total.add(n.resources_total)
                avail = avail.add(n.resources_available)
        return {"total": total.to_dict(), "available": avail.to_dict()}

    # ---- jobs ----------------------------------------------------------
    async def rpc_register_job(self, conn, p):
        if p.get("job_id"):
            # driver re-attaching after a GCS restart keeps its identity so
            # actor/object ownership and namespaces stay coherent
            job_id = JobID(p["job_id"])
            entry = self.jobs.get(job_id) or {"start_time": time.time()}
            entry.update({"state": "RUNNING", "driver_pid": p.get("pid")})
            self.jobs[job_id] = entry
        else:
            job_id = JobID.random()
            self.jobs[job_id] = {
                "state": "RUNNING",
                "start_time": time.time(),
                "driver_pid": p.get("pid"),
            }
        self._conn_job[conn] = job_id
        return {"job_id": job_id.binary()}

    # ---- workers (register their duplex conns for GCS-initiated pushes)
    async def rpc_dump_worker_stacks(self, conn, p):
        """Per-thread Python stacks of a live worker (reference role:
        dashboard py-spy profiling, reporter/profile_manager.py:83)."""
        wid = WorkerID(p["worker_id"])
        wconn = self._worker_conns.get(wid)
        if wconn is None or wconn.closed:
            raise rpc.RpcError(f"worker {wid.hex()[:12]} not connected")
        return await asyncio.wait_for(
            wconn.call("dump_stacks", {}), timeout=15.0
        )

    async def rpc_register_worker(self, conn, p):
        self._worker_conns[WorkerID(p["worker_id"])] = conn
        # workers/drivers share their node's fate under a partition:
        # label the conn so the link-cut site can match it
        if p.get("node_id"):
            conn.peer_endpoint = p["node_id"]
        return True

    # ---- chaos (network-partition installs; see common/faults.py) ------
    async def rpc_chaos_partition(self, conn, p):
        from ray_tpu.common import faults

        faults.cut_link(p["src"], p["dst"], p.get("duration_s"))
        return True

    async def rpc_chaos_heal(self, conn, p):
        from ray_tpu.common import faults

        faults.heal_link(p.get("src"), p.get("dst"))
        return True

    # ---- kv ------------------------------------------------------------
    async def rpc_kv_put(self, conn, p):
        key = p["key"]
        if p.get("overwrite", True) or key not in self.kv:
            self.kv[key] = p["value"]
            return True
        return False

    async def rpc_kv_get(self, conn, p):
        return self.kv.get(p["key"])

    async def rpc_kv_del(self, conn, p):
        return self.kv.pop(p["key"], None) is not None

    async def rpc_kv_exists(self, conn, p):
        return p["key"] in self.kv

    async def rpc_kv_keys(self, conn, p):
        prefix = p.get("prefix", "")
        return [k for k in self.kv if k.startswith(prefix)]

    # ---- object directory ---------------------------------------------
    async def rpc_add_object_location(self, conn, p):
        oid = p["object_id"]
        # a zombie raylet's announce must not re-enter the directory:
        # its arena is about to be (or was) discarded by the fence
        self._check_node_fence(NodeID(p["node_id"]), p.get("incarnation"))
        if oid in self._freed_tombstones:
            return False  # announce raced the free; do not resurrect
        self.object_locations.setdefault(oid, set()).add(NodeID(p["node_id"]))
        if "size" in p:
            self.object_sizes[oid] = p["size"]
        for fut in self._location_waiters.pop(oid, ()):
            if not fut.done():
                fut.set_result(True)
        return True

    async def rpc_add_spilled_location(self, conn, p):
        oid = p["object_id"]
        self._check_node_fence(NodeID(p["node_id"]), p.get("incarnation"))
        # A spill can race the object's free: the raylet picked the victim
        # before delete_objects arrived.  Registering a spilled location
        # for a freed object would orphan the file forever — refuse, and
        # the raylet keeps its arena copy (the pending delete reclaims it).
        if oid in self._freed_tombstones or (
            not self.object_holders.get(oid)
            and oid not in self.object_locations
        ):
            return {"ok": False}
        self.spilled_objects[oid] = NodeID(p["node_id"])
        if "size" in p:
            self.object_sizes[oid] = p["size"]
        for fut in self._location_waiters.pop(oid, ()):
            if not fut.done():
                fut.set_result(True)
        return {"ok": True}

    async def rpc_remove_object_location(self, conn, p):
        oid = p["object_id"]
        locs = self.object_locations.get(oid)
        if locs:
            locs.discard(NodeID(p["node_id"]))
            if not locs:
                del self.object_locations[oid]
        return True

    async def rpc_get_object_locations(self, conn, p):
        oid = p["object_id"]
        timeout = p.get("timeout", 0)
        locs = self.object_locations.get(oid)
        if not locs and oid not in self.spilled_objects and timeout:
            fut = asyncio.get_running_loop().create_future()
            self._location_waiters.setdefault(oid, []).append(fut)
            try:
                await asyncio.wait_for(fut, timeout=timeout)
            except asyncio.TimeoutError:
                pass
            locs = self.object_locations.get(oid)
        out = []
        for nid in locs or ():
            node = self.nodes.get(nid)
            if node and node.alive:
                # pullers prefer non-suspect copies: a stalled/partition-
                # suspected node would cost a full pull timeout per try
                out.append({
                    "node_id": nid.hex(),
                    "address": node.address,
                    "suspect": node.suspect,
                })
        spilled = None
        snid = self.spilled_objects.get(oid)
        if snid is not None:
            node = self.nodes.get(snid)
            if node and node.alive:
                spilled = {"node_id": snid.hex(), "address": node.address}
        return {
            "locations": out,
            "size": self.object_sizes.get(oid),
            "spilled": spilled,
        }

    async def rpc_free_objects(self, conn, p):
        for oid in p["object_ids"]:
            await self._free_object(oid)
        return True

    #: sub-methods a client may batch into one object_notify_batch rpc —
    #: the flush-window transport for high-churn object bookkeeping
    _BATCHABLE_OBJECT_RPCS = frozenset({
        "add_object_location", "remove_object_location", "free_objects",
        "ref_edge", "ref_update",
    })

    async def rpc_object_notify_batch(self, conn, p):
        """Apply a client's buffered object-directory notifies in arrival
        order (one rpc per flush window instead of one per task/object).
        Order matters: e.g. an add_object_location buffered before a
        free_objects must land first so the free's node fan-out sees the
        location."""
        for method, payload in p["items"]:
            if method not in self._BATCHABLE_OBJECT_RPCS:
                raise rpc.RpcError(
                    f"non-batchable method {method!r} in object_notify_batch"
                )
            await getattr(self, f"rpc_{method}")(conn, payload)
        return True

    async def _free_object(self, oid: bytes):
        self._mark_objects_dirty()
        self._freed_tombstones[oid] = None
        while len(self._freed_tombstones) > 10_000:
            self._freed_tombstones.popitem(last=False)
        locs = self.object_locations.pop(oid, set())
        self.object_sizes.pop(oid, None)
        self.object_holders.pop(oid, None)
        spilled_nid = self.spilled_objects.pop(oid, None)
        if spilled_nid is not None:
            locs = set(locs)
            locs.add(spilled_nid)  # its raylet also removes the spill file
        for nid in locs:
            node = self.nodes.get(nid)
            if node and node.alive:
                try:
                    await node.conn.notify(
                        "delete_objects", {"object_ids": [oid]}
                    )
                except Exception:
                    pass
        # a freed parent releases its nested (borrowed) children
        token = b"obj:" + oid
        for child in self.object_edges.pop(oid, ()):
            s = self.object_holders.get(child)
            if s is not None:
                s.discard(token)
                if not s:
                    self._schedule_free(child)

    async def rpc_ref_edge(self, conn, p):
        """A stored object contains serialized refs to children: pin the
        children for as long as the parent object exists."""
        parent = p["parent"]
        token = b"obj:" + parent
        kids = self.object_edges.setdefault(parent, [])
        for child in p.get("children", ()):
            if child not in kids:
                kids.append(child)
                self.object_holders.setdefault(child, set()).add(token)
        return True

    # ---- distributed refcounting ---------------------------------------
    async def rpc_ref_update(self, conn, p):
        holder = p["holder"]
        for oid in p.get("add", ()):
            self.object_holders.setdefault(oid, set()).add(holder)
        for oid in p.get("del", ()):
            s = self.object_holders.get(oid)
            if s is not None:
                s.discard(holder)
                if not s:
                    self._schedule_free(oid)
        return True

    def _schedule_free(self, oid: bytes):
        """Free after a grace window, re-checking holders — an in-flight
        ref_add from a borrower that deserialized the ref moments ago must
        win over a racing release."""
        if oid in self._free_scheduled:
            return
        self._free_scheduled.add(oid)

        def _maybe_free():
            self._free_scheduled.discard(oid)
            s = self.object_holders.get(oid)
            if s is not None and not s:
                asyncio.get_event_loop().create_task(self._free_object(oid))

        asyncio.get_event_loop().call_later(cfg.gcs_free_delay_s, _maybe_free)

    def _scrub_holder(self, holder: bytes):
        """A process died: remove it from every holder set."""
        self._mark_objects_dirty()
        for oid, s in list(self.object_holders.items()):
            if holder in s:
                s.discard(holder)
                if not s:
                    self._schedule_free(oid)

    # ---- placement groups ----------------------------------------------
    def _bundle_order(self, pg: PlacementGroupEntry, indices: List[int]) -> List[int]:
        """Place big bundles first (first-fit-decreasing)."""
        return sorted(
            indices,
            key=lambda i: -sum(pg.bundles[i]._fp.values()),
        )

    def _place_bundles(
        self, pg: PlacementGroupEntry, include_suspect: bool = False
    ) -> Optional[Dict[int, NodeID]]:
        """Choose a node for every unplaced bundle, or None if impossible now.

        Works against a scratch copy of availability so the decision is
        atomic: either every missing bundle fits, or nothing is reserved.
        (The reference does this with a 2-phase prepare/commit across
        raylets — bundle_scheduling_policy.cc; here one atomic pass.)
        Suspect nodes are excluded unless ``include_suspect`` — the
        caller retries with them only when healthy capacity can't place
        the gang (a transient stall must not block PG creation, but it
        must not attract fresh gangs either).
        """
        alive = {
            n.node_id: n
            for n in self.nodes.values()
            if n.alive and n.conn is not None and not n.draining
            and (include_suspect or not n.suspect)
        }
        avail = {nid: n.resources_available for nid, n in alive.items()}
        missing = [i for i in range(len(pg.bundles)) if pg.bundle_nodes[i] is None]
        used: Set[NodeID] = {nid for nid in pg.bundle_nodes if nid is not None}
        assignment: Dict[int, NodeID] = {}

        def util(nid: NodeID) -> float:
            return avail[nid].utilization(alive[nid].resources_total)

        if pg.strategy == "STRICT_PACK":
            total = ResourceSet()
            for b in pg.bundles:
                total = total.add(b)
            cands = [nid for nid, a in avail.items() if a.covers(total)]
            if not cands:
                return None
            nid = max(cands, key=util)  # binpack: densest feasible node
            return {i: nid for i in missing}

        for i in self._bundle_order(pg, missing):
            b = pg.bundles[i]
            feas = [nid for nid, a in avail.items() if a.covers(b)]
            fresh = [nid for nid in feas if nid not in used]
            if pg.strategy == "STRICT_SPREAD":
                if not fresh:
                    return None
                nid = min(fresh, key=util)  # emptiest distinct node
            elif pg.strategy == "SPREAD":
                pool = fresh or feas
                if not pool:
                    return None
                nid = min(pool, key=util)
            else:  # PACK: fewest nodes — prefer nodes this pg already uses
                pool = [nid for nid in feas if nid in used] or feas
                if not pool:
                    return None
                nid = max(pool, key=util)
            assignment[i] = nid
            avail[nid] = avail[nid].subtract(b)
            used.add(nid)
        return assignment

    def _try_place_pg(self, pg: PlacementGroupEntry) -> bool:
        assignment = self._place_bundles(pg)
        if assignment is None and any(
            n.suspect and n.alive and n.conn is not None and not n.draining
            for n in self.nodes.values()
        ):
            # healthy capacity can't place the gang: fall back to
            # suspect nodes rather than park the PG behind a stall
            assignment = self._place_bundles(pg, include_suspect=True)
        if assignment is None:
            return False
        for i, nid in assignment.items():
            node = self.nodes[nid]
            node.resources_available = node.resources_available.subtract(
                pg.bundles[i]
            )
            pg.bundle_nodes[i] = nid
            pg.bundle_available[i] = pg.bundles[i]
        pg.state = PG_CREATED
        self._wake_pg_waiters(pg.pg_id)
        return True

    def _wake_pg_waiters(self, pg_id: PlacementGroupID):
        for fut in self._pg_state_waiters.pop(pg_id, ()):
            if not fut.done():
                fut.set_result(True)

    async def _pg_state_wait(self, pg_id: PlacementGroupID, timeout: float) -> bool:
        fut = asyncio.get_running_loop().create_future()
        self._pg_state_waiters.setdefault(pg_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout=timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def rpc_create_placement_group(self, conn, p):
        pg_id = PlacementGroupID(p["pg_id"])
        existing = self.placement_groups.get(pg_id)
        if existing is not None and existing.state != PG_REMOVED:
            # retry of a create that already landed (checkpoint flushed,
            # GCS crashed before the reply) — idempotent success
            return {"state": existing.state}
        strategy = p.get("strategy", "PACK")
        if strategy not in PG_STRATEGIES:
            raise rpc.RpcError(f"unknown placement strategy {strategy!r}")
        bundles = [ResourceSet(b) for b in p["bundles"]]
        if not bundles or any(b.is_empty() for b in bundles):
            raise rpc.RpcError("placement group bundles must be non-empty")
        name = p.get("name") or None
        ns = p.get("namespace", "default")
        if name:
            key = (ns, name)
            if key in self.named_pgs:
                existing = self.placement_groups.get(self.named_pgs[key])
                if existing and existing.state != PG_REMOVED:
                    raise rpc.RpcError(f"placement group name {name!r} already taken")
            self.named_pgs[key] = pg_id
        pg = PlacementGroupEntry(
            pg_id=pg_id,
            name=name,
            strategy=strategy,
            bundles=bundles,
            state=PG_PENDING,
            owner_job=JobID(p["job_id"]) if p.get("job_id") else None,
            detached=p.get("detached", False),
            bundle_nodes=[None] * len(bundles),
            bundle_available=[ResourceSet() for _ in bundles],
            namespace=ns,
        )
        self.placement_groups[pg_id] = pg
        if not self._try_place_pg(pg):
            self._pending_pgs.append(pg_id)
        await self.publish(
            "placement_groups", {"event": "created", "pg_id": pg_id.hex()}
        )
        return {"state": pg.state}

    async def rpc_wait_placement_group_ready(self, conn, p):
        pg = self.placement_groups.get(PlacementGroupID(p["pg_id"]))
        if pg is None:
            raise rpc.RpcError("placement group not found")
        deadline = time.monotonic() + p.get("timeout", 30.0)
        while pg.state not in (PG_CREATED, PG_REMOVED):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"state": pg.state}
            await self._pg_state_wait(pg.pg_id, remaining)
        if pg.state == PG_REMOVED:
            raise rpc.RpcError("placement group was removed while waiting")
        return {"state": pg.state}

    async def rpc_remove_placement_group(self, conn, p):
        pg = self.placement_groups.get(PlacementGroupID(p["pg_id"]))
        if pg is None or pg.state == PG_REMOVED:
            return True
        await self._remove_pg(pg)
        return True

    async def _remove_pg(self, pg: PlacementGroupEntry):
        # State first: _release_lease consults it to decide where freed
        # resources go (bundle vs node pool).
        pg.state = PG_REMOVED
        if pg.name:
            self.named_pgs.pop((pg.namespace, pg.name), None)
        # Kill actors and break leases living in the group (the reference
        # kills workers of removed PGs: gcs_placement_group_manager.cc).
        for lease in list(self.leases.values()):
            if lease.pg_ref and lease.pg_ref[0] == pg.pg_id:
                if lease.actor_id:
                    actor = self.actors.get(lease.actor_id)
                    if actor:
                        await self._kill_actor(
                            actor, "placement group removed", no_restart=True
                        )
                        continue  # _kill_actor released the lease
                await self._release_lease(lease.lease_id, broken=True)
        # Return unleased bundle remainders to their nodes.
        for i, nid in enumerate(pg.bundle_nodes):
            if nid is not None:
                node = self.nodes.get(nid)
                if node and node.alive:
                    node.resources_available = node.resources_available.add(
                        pg.bundle_available[i]
                    )
            pg.bundle_nodes[i] = None
            pg.bundle_available[i] = ResourceSet()
        if pg.pg_id in self._pending_pgs:
            self._pending_pgs.remove(pg.pg_id)
        self._wake_pg_waiters(pg.pg_id)
        await self.publish(
            "placement_groups", {"event": "removed", "pg_id": pg.pg_id.hex()}
        )
        self._kick_pending()

    async def rpc_get_placement_group(self, conn, p):
        if "name" in p:
            key = (p.get("namespace", "default"), p["name"])
            pg_id = self.named_pgs.get(key)
            pg = self.placement_groups.get(pg_id) if pg_id else None
        else:
            pg = self.placement_groups.get(PlacementGroupID(p["pg_id"]))
        if pg is None:
            return None
        return self._pg_info(pg)

    def _pg_info(self, pg: PlacementGroupEntry) -> dict:
        return {
            "pg_id": pg.pg_id.binary(),
            "name": pg.name,
            "strategy": pg.strategy,
            "state": pg.state,
            "bundles": [b.to_dict() for b in pg.bundles],
            "bundle_nodes": [
                nid.hex() if nid else None for nid in pg.bundle_nodes
            ],
            "bundles_available": [b.to_dict() for b in pg.bundle_available],
            "created_at": pg.created_at,
        }

    async def rpc_list_placement_groups(self, conn, p):
        return [self._pg_info(pg) for pg in self.placement_groups.values()]

    # ---- blob store (runtime-env packages and other large artifacts;
    # files under the session dir, so they survive GCS restarts without
    # riding the control checkpoint) ------------------------------------
    def _blob_path(self, sha: str) -> str:
        import os

        base = self.session_dir or "/tmp/ray_tpu"
        return os.path.join(base, "blobs", sha)

    async def rpc_put_blob(self, conn, p):
        import os

        sha = p["sha"]
        path = self._blob_path(sha)

        def write():
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(p["data"])
            os.replace(tmp, path)

        if not os.path.exists(path):
            await asyncio.get_running_loop().run_in_executor(None, write)
        return True

    async def rpc_get_blob(self, conn, p):
        path = self._blob_path(p["sha"])

        def read():
            try:
                with open(path, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                return None

        return await asyncio.get_running_loop().run_in_executor(None, read)

    # ---- job submission (ray: dashboard/modules/job/job_manager.py:529,
    # embedded here instead of a dashboard process) ---------------------
    async def rpc_submit_job(self, conn, p):
        import os
        import subprocess
        import uuid

        sub_id = p.get("submission_id") or f"rtjob-{uuid.uuid4().hex[:12]}"
        if sub_id in self.submitted_jobs:
            raise rpc.RpcError(f"submission_id {sub_id!r} already used")
        base = self.session_dir or "/tmp/ray_tpu"
        jobs_dir = os.path.join(base, "jobs", sub_id)
        os.makedirs(jobs_dir, exist_ok=True)
        env = dict(os.environ)
        env["RT_ADDRESS"] = self.address
        env.pop("JAX_PLATFORMS", None)  # driver decides its own platform
        cwd = jobs_dir
        desc = p.get("runtime_env") or {}
        env.update(desc.get("env_vars") or {})
        if desc.get("working_dir_pkg"):
            import io
            import zipfile

            blob = await self.rpc_get_blob(
                conn, {"sha": desc["working_dir_pkg"]}
            )
            if blob is None:
                raise rpc.RpcError("job working_dir package missing")
            cwd = os.path.join(jobs_dir, "working_dir")
            await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: zipfile.ZipFile(io.BytesIO(bytes(blob))).extractall(
                    cwd
                ),
            )
        log_path = os.path.join(jobs_dir, "driver.log")

        def launch():
            log_f = open(log_path, "ab")
            try:
                return subprocess.Popen(
                    ["bash", "-c", p["entrypoint"]],
                    cwd=cwd, env=env, stdout=log_f,
                    stderr=subprocess.STDOUT,
                )
            finally:
                log_f.close()

        proc = await asyncio.get_running_loop().run_in_executor(None, launch)
        self.submitted_jobs[sub_id] = {
            "submission_id": sub_id,
            "entrypoint": p["entrypoint"],
            "metadata": p.get("metadata", {}),
            "status": RUNNING_JOB,
            "start_time": time.time(),
            "end_time": None,
            "log_path": log_path,
            "pid": proc.pid,
            "_proc": proc,
        }
        self._mark_dirty()
        return {"submission_id": sub_id}

    def _poll_submitted_jobs(self):
        for info in self.submitted_jobs.values():
            proc = info.get("_proc")
            if info["status"] == RUNNING_JOB and proc is not None:
                rc = proc.poll()
                if rc is not None:
                    info["status"] = (
                        SUCCEEDED_JOB if rc == 0 else FAILED_JOB
                    )
                    info["end_time"] = time.time()
                    info["returncode"] = rc
                    self._mark_dirty()

    async def rpc_get_job_info(self, conn, p):
        self._poll_submitted_jobs()
        info = self.submitted_jobs.get(p["submission_id"])
        if info is None:
            raise rpc.RpcError(f"no job {p['submission_id']!r}")
        return {k: v for k, v in info.items() if not k.startswith("_")}

    async def rpc_get_job_logs(self, conn, p):
        info = self.submitted_jobs.get(p["submission_id"])
        if info is None:
            raise rpc.RpcError(f"no job {p['submission_id']!r}")

        def read():
            try:
                with open(info["log_path"], "rb") as f:
                    return f.read().decode("utf-8", "replace")
            except FileNotFoundError:
                return ""

        # off-loop: a multi-GB driver log must not stall heartbeats
        return await asyncio.get_running_loop().run_in_executor(None, read)

    async def rpc_stop_job(self, conn, p):
        info = self.submitted_jobs.get(p["submission_id"])
        if info is None:
            return False
        proc = info.get("_proc")
        if info["status"] == RUNNING_JOB and proc is not None:
            proc.terminate()

            def wait_or_kill():
                try:
                    proc.wait(timeout=5)
                except Exception:
                    proc.kill()

            # off-loop: an entrypoint ignoring SIGTERM must not stall the
            # control plane for the grace period
            await asyncio.get_running_loop().run_in_executor(
                None, wait_or_kill
            )
            info["status"] = STOPPED_JOB
            info["end_time"] = time.time()
            self._mark_dirty()
        return True

    async def rpc_list_jobs(self, conn, p):
        self._poll_submitted_jobs()
        return [
            {k: v for k, v in info.items() if not k.startswith("_")}
            for info in self.submitted_jobs.values()
        ]

    async def rpc_delete_job(self, conn, p):
        """Drop a TERMINAL submitted job's record (reference:
        DELETE /api/jobs/{id}, job_head.py:368 — running jobs must be
        stopped first)."""
        self._poll_submitted_jobs()
        info = self.submitted_jobs.get(p["submission_id"])
        if info is None:
            return False
        if info["status"] == RUNNING_JOB:
            raise rpc.RpcError(
                f"job {p['submission_id']!r} is RUNNING; stop it first"
            )
        del self.submitted_jobs[p["submission_id"]]
        self._mark_dirty()
        return True

    async def rpc_list_tasks(self, conn, p):
        """Cluster-wide live tasks: fan out to raylets → workers (ray:
        python/ray/util/state/api.py list_tasks, sourced live instead of
        from an event store)."""
        out = []
        for n in list(self.nodes.values()):
            if not n.alive or n.conn is None:
                continue
            try:
                out.extend(
                    await n.conn.call("list_worker_tasks", {}, timeout=10.0)
                )
            except Exception:
                continue
        return out

    async def rpc_list_objects(self, conn, p):
        """Object directory view (id, size, locations, holder count)."""
        limit = p.get("limit", 1000)
        out = []
        for oid, nodes in list(self.object_locations.items())[:limit]:
            out.append({
                "object_id": oid.hex(),
                "size_bytes": self.object_sizes.get(oid),
                "locations": [n.hex() for n in nodes],
                "num_holders": len(self.object_holders.get(oid, ())),
            })
        return out

    def record_cluster_event(self, severity: str, source: str,
                             message: str, **fields) -> None:
        """Append a structured event to the bounded cluster event log
        (ray: src/ray/util/event.h RAY_EVENT + dashboard/modules/event).
        Core transitions (node/actor/worker lifecycle) record here
        automatically; applications report via util.events."""
        self._events.append({
            "ts": time.time(),
            "severity": severity,
            "source": source,
            "message": message,
            **fields,
        })
        while len(self._events) > 2000:
            self._events.pop(0)

    async def rpc_cluster_store_stats(self, conn, p):
        """Per-node shm store stats fanned out to live raylets (ray:
        `ray memory` / memory_summary role)."""
        alive = [
            n for n in self.nodes.values()
            if n.alive and n.conn is not None
        ]

        async def one(node):
            try:
                return node.node_id.hex(), await asyncio.wait_for(
                    node.conn.call("store_stats", {}), timeout=10.0
                )
            except Exception as e:  # noqa: BLE001 — report per-node
                return node.node_id.hex(), {"error": repr(e)}

        # concurrent fan-out: one hung raylet costs 10s total, not 10s
        # per node
        return dict(await asyncio.gather(*(one(n) for n in alive)))

    async def rpc_report_event(self, conn, p):
        self.record_cluster_event(
            p.get("severity", "INFO"), p.get("source", "app"),
            p.get("message", ""), **(p.get("fields") or {}),
        )
        return True

    async def rpc_list_events(self, conn, p):
        sev = p.get("severity")
        rows = [
            e for e in self._events
            if sev is None or e["severity"] == sev
        ]
        limit = int(p.get("limit", 500))
        return rows[-limit:] if limit > 0 else []

    async def rpc_metrics_push(self, conn, p):
        """A process pushes its metric snapshot (ray: stats exporter →
        dashboard agent; here straight into the GCS aggregate table)."""
        self.metrics_by_reporter[p["reporter"]] = {
            "ts": time.time(),
            "metrics": p["metrics"],
        }
        return True

    async def rpc_get_metrics(self, conn, p):
        """Aggregated metrics: counters/histogram buckets sum across
        reporters, gauges keep per-reporter last values."""
        agg: Dict[str, Any] = {}
        for reporter, snap in self.metrics_by_reporter.items():
            for m in snap["metrics"]:
                key = m["name"]
                ent = agg.setdefault(
                    key,
                    {"name": key, "type": m["type"],
                     "description": m.get("description", ""),
                     "series": {}},
                )
                for tags_key, value in m["series"].items():
                    if m["type"] == "gauge":
                        ent["series"][f"{reporter}|{tags_key}"] = value
                    else:
                        ent["series"][tags_key] = (
                            ent["series"].get(tags_key, 0) + value
                        )
        return list(agg.values())

    async def rpc_scheduler_stats(self, conn, p):
        """O(1) control-plane counters (queue depth, leases, nodes,
        actors, PGs) — the cheap probe for dashboards and scale tests;
        get_autoscaler_state serializes the full pending list and is
        O(queue), unusable at 1M queued."""
        return {
            "pending_leases": len(self.scheduler.pending),
            "leases": len(self.leases),
            "nodes": len(self.nodes),
            "nodes_alive": sum(
                1 for n in self.nodes.values()
                if n.alive and n.conn is not None
            ),
            "actors": len(self.actors),
            "placement_groups": sum(
                1 for pg in self.placement_groups.values()
                if pg.state != PG_REMOVED
            ),
        }

    async def rpc_get_autoscaler_state(self, conn, p):
        """Demand/usage view for the autoscaler's reconcile loop (ray:
        autoscaler/v2 GetClusterResourceState — scheduler.py:624)."""
        pending = [
            {"demand": pl.demand.to_dict(), "strategy": pl.strategy,
             "age_s": time.monotonic() - pl.enqueued_at}
            for pl in self.scheduler.pending
        ]
        pending_bundles = []
        for pg in self.placement_groups.values():
            if pg.state in (PG_PENDING, PG_RESCHEDULING):
                pending_bundles.append({
                    "pg_id": pg.pg_id.hex(),
                    "strategy": pg.strategy,
                    "bundles": [
                        pg.bundles[i].to_dict()
                        for i in range(len(pg.bundles))
                        if pg.bundle_nodes[i] is None
                    ],
                })
        busy_nodes: Set[NodeID] = set()
        for lease in self.leases.values():
            busy_nodes.add(lease.node_id)
        for a in self.actors.values():
            if a.state in (ACTOR_ALIVE, ACTOR_RESTARTING) and a.node_id:
                busy_nodes.add(a.node_id)
        for pg in self.placement_groups.values():
            if pg.state != PG_REMOVED:
                busy_nodes.update(n for n in pg.bundle_nodes if n)
        nodes = [
            {
                "node_id": n.node_id.hex(),
                "alive": n.alive and n.conn is not None,
                # suspect nodes still COUNT as supply (autoscaler: a
                # transient stall must not launch replacement capacity)
                # but must not be idle-drained while their fate is open
                "suspect": n.suspect,
                "draining": n.draining,
                "labels": n.labels,
                "resources_total": n.resources_total.to_dict(),
                "resources_available": n.resources_available.to_dict(),
                "idle": n.node_id not in busy_nodes,
            }
            for n in self.nodes.values()
        ]
        return {
            "pending_leases": pending,
            "pending_pg_bundles": pending_bundles,
            "nodes": nodes,
        }

    # ---- graceful drain (protocol v2) -----------------------------------
    #
    # DrainNode role-equivalent (ray: NodeInfoGcsService DrainNode,
    # gcs_node_manager.cc) extended into zero-loss migration: a DRAINING
    # node is excluded from lease grants and PG (re)placement, then —
    # inside the announced deadline — its PG bundles are relocated, its
    # sole-copy shm objects are pulled onto surviving nodes (so
    # object_locations never goes empty: no lineage reconstruction), and
    # its actors migrate (checkpoint hooks → state handoff that does not
    # consume the restart budget; hook-less → fresh restart under
    # max_restarts; no budget → left to serve until the kill).  On
    # deadline expiry the GCS falls back to the hard _on_node_death path,
    # so a stuck drain can never wedge the cluster.

    @staticmethod
    def _ckpt_key(actor_id: ActorID) -> str:
        return f"__rt_actor_ckpt:{actor_id.hex()}"

    async def _drop_actor_ckpt(self, actor_id: ActorID) -> None:
        """Retire an actor's parked drain checkpoint: pop the KV record
        and, when the blob rode the object plane, free the blob object
        cluster-wide (its copies would otherwise pin arena space as
        protected primaries forever)."""
        import pickle

        raw = self.kv.pop(self._ckpt_key(actor_id), None)
        if raw is None:
            return
        self._mark_dirty()
        try:
            ref = pickle.loads(raw).get("blob_ref")
        except Exception:
            return
        if ref is not None:
            await self._free_object(ref)

    async def rpc_drain_node(self, conn, p):
        """Start a graceful drain: stop scheduling onto the node, then
        migrate its state within ``deadline_s``.  The node stays alive
        until its raylet actually dies (or the deadline lapses), so
        _on_node_death can still scrub whatever the drain did not move."""
        node = self.nodes.get(NodeID.from_hex(p["node_id"]))
        if node is None or not node.alive:
            return {"accepted": False, "state": "unknown"}
        reason = p.get("reason", "idle")
        deadline_s = float(
            p.get("deadline_s") or cfg.drain_deadline_default_s
        )
        if node.draining:
            # idempotent re-request (a metadata watcher re-announcing):
            # report the in-flight drain instead of restarting it
            st = node.drain_status or {}
            return {"accepted": True, "state": st.get("state", "draining")}
        node.drain_reason = reason
        node.drain_status = {
            "state": "draining",
            "reason": reason,
            "deadline_s": deadline_s,
            "started_at": time.time(),
            "objects_total": 0,
            "objects_moved": 0,
            "actors_total": 0,
            "actors_moved": 0,
            "ckpt_blob_objects": 0,
        }
        node.draining = True  # parks the node in the scheduler index
        self.record_cluster_event(
            "WARNING", "gcs",
            f"node draining ({reason}, deadline {deadline_s:g}s)",
            node_id=node.node_id.hex(),
        )
        await self.publish(
            "nodes",
            {"event": "draining", "node_id": p["node_id"],
             "reason": reason, "deadline_s": deadline_s},
        )
        self._drain_tasks[node.node_id] = (
            asyncio.get_running_loop().create_task(
                self._drain_node(node, deadline_s)
            )
        )
        return {"accepted": True, "state": "draining"}

    async def rpc_get_drain_status(self, conn, p):
        node = self.nodes.get(NodeID.from_hex(p["node_id"]))
        if node is None:
            return {"state": "unknown"}
        if not node.alive:
            return dict(node.drain_status or {}, state="dead")
        if node.drain_status is None:
            return {"state": "none"}
        return dict(node.drain_status)

    async def _drain_node(self, node: NodeEntry, deadline_s: float):
        """Deadline-bounded drain driver: on success the node sits fully
        evacuated (still alive, still excluded) awaiting its kill; on
        timeout or error the hard node-death path cleans up reactively."""
        st = node.drain_status
        try:
            await asyncio.wait_for(
                self._drain_node_inner(node, deadline_s), timeout=deadline_s
            )
        except Exception as e:  # noqa: BLE001 — incl. wait_for timeout
            st["state"] = "failed"
            st["error"] = repr(e)
            logger.warning(
                "drain of node %s failed (%r); falling back to hard "
                "node-death cleanup", node.node_id, e,
            )
            self._drain_tasks.pop(node.node_id, None)
            await self._on_node_death(
                node.node_id, f"drain deadline expired/failed: {e!r}"
            )
            return
        finally:
            self._drain_tasks.pop(node.node_id, None)
            self._mark_dirty()
        st["state"] = "drained"
        st["finished_at"] = time.time()
        self.record_cluster_event(
            "INFO", "gcs",
            f"node drained ({st['reason']}): {st['objects_moved']} objects, "
            f"{st['actors_moved']} actors migrated",
            node_id=node.node_id.hex(),
        )
        await self.publish(
            "nodes", {"event": "drained", "node_id": node.node_id.hex()}
        )

    async def _drain_node_inner(self, node: NodeEntry, deadline_s: float):
        budget_end = time.monotonic() + deadline_s
        # 1. the raylet stops accepting leases and lets in-flight tasks
        # finish (GCS-side exclusion is authoritative; this closes the
        # grant-in-flight window and arms the raylet's local refusals)
        try:
            await node.conn.call(
                "drain",
                {"reason": node.drain_reason, "deadline_s": deadline_s},
                timeout=5.0,
            )
        except Exception:
            logger.warning("raylet drain notify failed", exc_info=True)
        # 2. relocate placement-group bundles living here: replacements
        # land on surviving nodes (draining nodes are excluded from
        # placement), so gang actors can restart into their own bundle
        await self._drain_evict_pg_bundles(node)
        # 3. evacuate sole-copy shm objects onto surviving nodes over the
        # existing pull plane — object_locations never goes empty, so no
        # get() ever needs lineage reconstruction
        await self._drain_evacuate_objects(node)
        # 4. migrate actors (checkpoint handoff / fresh restart)
        await self._drain_migrate_actors(node)
        # 5. give in-flight normal-task leases a bounded window to return
        # naturally (clients return leases shortly after their queue
        # drains); whatever remains is broken by the eventual node death,
        # riding the task retry path
        lease_grace = max(
            0.0,
            min(
                (budget_end - time.monotonic()),
                deadline_s * cfg.drain_lease_wait_frac,
            ),
        )
        # actor leases are excluded: migrated actors' leases were already
        # released above, and the ones that legitimately remain
        # (on_drain="ignore", no restart budget) live until the node
        # dies — waiting on them would burn the whole grace for nothing
        lease_end = time.monotonic() + lease_grace
        while time.monotonic() < lease_end:
            if node.inflight_grants == 0 and not any(
                lease.node_id == node.node_id and lease.actor_id is None
                for lease in self.leases.values()
            ):
                break
            await asyncio.sleep(0.05)
        # 6. re-scan evacuation: a task that was in flight at phase 3
        # may have stored a sole-copy result on the node since the first
        # sweep — it must not be lost to the kill (the second pass is
        # incremental: usually zero victims)
        await self._drain_evacuate_objects(node)

    async def _drain_evict_pg_bundles(self, node: NodeEntry):
        nid = node.node_id
        moved = False
        for pg in list(self.placement_groups.values()):
            if pg.state not in (PG_CREATED, PG_RESCHEDULING):
                continue
            lost = [
                i for i, bn in enumerate(pg.bundle_nodes) if bn == nid
            ]
            if not lost:
                continue
            # break non-actor leases drawing from the evicted bundles —
            # their tasks requeue onto the relocated bundle (actor leases
            # are handled by the migration phase, which releases them
            # itself once the actor's state is safe)
            for lease in list(self.leases.values()):
                if (
                    lease.node_id == nid
                    and lease.pg_ref is not None
                    and lease.pg_ref[0] == pg.pg_id
                    and lease.pg_ref[1] in lost
                    and lease.actor_id is None
                ):
                    await self._release_lease(
                        lease.lease_id, broken=True, kick=False
                    )
            for i in lost:
                # accounting: only the UNLEASED remainder returns to the
                # (parked) node pool — outstanding draws (gang-actor
                # leases) are credited by their own _release_lease when
                # the migration phase frees them, and the full bundle
                # here would double-count them past resources_total
                node.resources_available = node.resources_available.add(
                    pg.bundle_available[i]
                )
                pg.bundle_nodes[i] = None
                pg.bundle_available[i] = ResourceSet()
            pg.state = PG_RESCHEDULING
            if pg.pg_id not in self._pending_pgs:
                self._pending_pgs.append(pg.pg_id)
            await self.publish(
                "placement_groups",
                {"event": "rescheduling", "pg_id": pg.pg_id.hex()},
            )
            moved = True
        if moved:
            self._kick_pending()  # place the evicted bundles elsewhere now

    def _drain_targets(self, node: NodeEntry) -> List[NodeEntry]:
        targets = [
            n for n in self.nodes.values()
            if n.alive and n.conn is not None and not n.draining
        ]
        # healthy targets first: evacuating onto a failure-suspected
        # node risks a second move (or a loss) moments later
        targets.sort(key=lambda n: n.suspect)
        return targets

    def _node_is_doomed(self, nid: NodeID) -> bool:
        n = self.nodes.get(nid)
        return n is None or not n.alive or n.draining

    async def _drain_evacuate_objects(self, node: NodeEntry):
        nid = node.node_id
        st = node.drain_status
        # an object needs evacuation when one copy is here and EVERY
        # copy sits on a doomed (draining/dead) node — exact `== {nid}`
        # would let an object replicated only across two concurrently
        # draining nodes (a whole preempted slice) be evacuated by
        # neither drain and lost to both kills; dual evacuation of the
        # same object is harmless (the targets' pulls coalesce)
        victims = [
            oid for oid, locs in self.object_locations.items()
            if nid in locs and all(self._node_is_doomed(l) for l in locs)
        ]
        sole = set(victims)
        for oid, snid in self.spilled_objects.items():
            # spilled-only objects (file on the draining node's disk, no
            # live arena copy on a surviving node): a target's pull
            # restores them straight off the spill file
            if snid == nid and oid not in sole and all(
                self._node_is_doomed(l)
                for l in self.object_locations.get(oid, ())
            ):
                victims.append(oid)
        # accumulate: the drain runs two sweeps (bulk + a post-settle
        # re-scan for results stored mid-drain)
        st["objects_total"] += len(victims)
        if not victims:
            return
        targets = self._drain_targets(node)
        if not targets:
            raise rpc.RpcError(
                "no surviving node to evacuate onto (sole-copy objects "
                "would be lost)"
            )
        sem = asyncio.Semaphore(cfg.drain_evac_concurrency)

        async def evacuate(i: int, oid: bytes):
            async with sem:
                # try each surviving node once, starting round-robin —
                # the outer deadline bounds total time
                errs = []
                for k in range(len(targets)):
                    t = targets[(i + k) % len(targets)]
                    try:
                        ok = await t.conn.call(
                            "pull_object",
                            {"object_id": oid, "timeout": 20.0},
                            timeout=30.0,
                        )
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)
                        continue
                    if ok is True:
                        st["objects_moved"] += 1
                        return
                raise rpc.RpcError(
                    f"evacuation of {oid.hex()[:12]} failed on every "
                    f"surviving node ({errs!r})"
                )

        await asyncio.gather(
            *(evacuate(i, oid) for i, oid in enumerate(victims))
        )

    async def _drain_migrate_actors(self, node: NodeEntry):
        import pickle

        nid = node.node_id
        st = node.drain_status
        victims = [
            a for a in self.actors.values()
            if a.node_id == nid and a.state == ACTOR_ALIVE
            and getattr(a, "on_drain", "migrate") != "ignore"
        ]
        st["actors_total"] = len(victims)
        for actor in victims:
            lease = self.leases.get(actor.lease_id)
            wconn = (
                self._worker_conns.get(lease.worker_id)
                if lease is not None else None
            )
            ck = {"supported": False, "blob": None, "groups": []}
            if wconn is not None and not wconn.closed:
                try:
                    # unbounded on purpose: a hung __rt_checkpoint__ is
                    # exactly what the outer drain deadline exists for
                    ck = await wconn.call(
                        "checkpoint_actor",
                        {"actor_id": actor.actor_id.binary()},
                        timeout=-1,
                    )
                except Exception:
                    logger.warning(
                        "checkpoint of actor %s failed; migrating fresh",
                        actor.actor_id, exc_info=True,
                    )
            groups = ck.get("groups") or []
            reason = f"node draining ({st['reason']})"
            if ck.get("supported"):
                # stateful migration: intentional relocation, NOT a
                # failure — does not consume the restart budget.  Large
                # blobs arrive as an object-plane ref (blob_ref): only
                # the id is parked in KV; the restore pulls the payload
                # over the data plane and _drop_actor_ckpt frees it.
                self.kv[self._ckpt_key(actor.actor_id)] = pickle.dumps(
                    {"blob": ck.get("blob"),
                     "blob_ref": ck.get("blob_ref"),
                     "groups": groups}, protocol=5
                )
                if ck.get("blob_ref") is not None:
                    st["ckpt_blob_objects"] = (
                        st.get("ckpt_blob_objects", 0) + 1
                    )
                self._mark_dirty()
            elif groups:
                # hook-less collective member: no user state to carry,
                # but the membership envelope still rides along so the
                # restarted process re-joins its groups
                self.kv[self._ckpt_key(actor.actor_id)] = pickle.dumps(
                    {"blob": None, "groups": groups}, protocol=5
                )
                self._mark_dirty()
            if not ck.get("supported"):
                can_restart = actor.max_restarts != 0 and (
                    actor.max_restarts < 0
                    or actor.restarts_used < actor.max_restarts
                )
                if not can_restart:
                    # no budget: leave it serving — it dies with the node
                    # exactly as it would today, and killing it early
                    # would only shorten its remaining service time.
                    # If the worker DID capture (its reply was lost), its
                    # admission fence is up — lift it, or "serving" would
                    # really be "parking every call until node death"
                    if wconn is not None and not wconn.closed:
                        try:
                            await wconn.notify("checkpoint_abort", {})
                        except Exception:
                            pass
                    await self._drop_actor_ckpt(actor.actor_id)
                    continue
                actor.restarts_used += 1
            actor.state = ACTOR_RESTARTING
            actor.worker_addr = None
            self.record_cluster_event(
                "WARNING", "gcs",
                f"actor migrating off draining node "
                f"({'with state' if ck.get('supported') else 'fresh'})",
                actor_id=actor.actor_id.hex(),
            )
            await self.publish(
                f"actor:{actor.actor_id.hex()}", {"state": ACTOR_RESTARTING}
            )
            old_lease = actor.lease_id
            actor.lease_id = None
            if old_lease is not None:
                # kills the old worker (its state is safe now); the
                # raylet's worker_died report finds no lease/ALIVE state
                # to act on, so no double restart
                await self._release_lease(old_lease, broken=True)
            # shielded: once the old worker is gone the restart targets a
            # SURVIVING node — a drain-deadline cancellation mid-restart
            # must let it finish rather than strand the actor RESTARTING
            # (strong ref held: the loop tracks tasks weakly, and an
            # orphaned shield inner would otherwise be GC-able)
            restart = asyncio.get_running_loop().create_task(
                self._restart_actor(actor, reason)
            )
            self._restart_tasks.add(restart)
            restart.add_done_callback(self._restart_tasks.discard)
            await asyncio.shield(restart)
            if actor.state == ACTOR_ALIVE:
                st["actors_moved"] += 1

    def _pg_bundle_candidates(
        self, pg: PlacementGroupEntry, idx: int, demand: ResourceSet
    ) -> List[int]:
        """Bundle indices this lease may draw from; validates feasibility.

        Raises immediately (like the non-PG infeasibility path) when the
        demand can never fit the targeted bundle(s), instead of letting the
        caller wait forever on LEASE_PENDING.
        """
        if idx >= len(pg.bundles):
            raise rpc.RpcError(
                f"bundle_index {idx} out of range ({len(pg.bundles)} bundles)"
            )
        cands = [idx] if idx >= 0 else list(range(len(pg.bundles)))
        if not any(pg.bundles[i].covers(demand) for i in cands):
            raise rpc.RpcError(
                f"infeasible placement-group request {demand.to_dict()}: no "
                f"targeted bundle is large enough "
                f"(bundles: {[pg.bundles[i].to_dict() for i in cands]})"
            )
        return cands

    async def _try_grant_pg_lease(
        self, pg: PlacementGroupEntry, cands: List[int], demand: ResourceSet,
        conn, p,
    ):
        """Grant from the first bundle with room on an alive node, else None."""
        if pg.state != PG_CREATED:
            return None
        for i in cands:
            nid = pg.bundle_nodes[i]
            node = self.nodes.get(nid) if nid else None
            # `not node.draining`: the general scheduler parks draining
            # nodes in its index, but PG grants bypass the index and
            # would otherwise keep placing fresh work onto a node the
            # autoscaler/provider is about to terminate
            if (node and node.alive and node.conn is not None
                    and not node.draining
                    and pg.bundle_available[i].covers(demand)):
                return await self._grant_lease(
                    node, demand, conn, p, pg_ref=(pg.pg_id, i)
                )
        return None

    async def _request_pg_lease(self, conn, p, demand: ResourceSet, strategy):
        pg = self.placement_groups.get(
            PlacementGroupID.from_hex(strategy["pg_id"])
        )
        if pg is None:
            raise rpc.RpcError("placement group not found")
        idx = strategy.get("bundle_index", -1)
        cands = self._pg_bundle_candidates(pg, idx, demand)
        deadline = time.monotonic() + cfg.sched_max_pending_lease_s
        while True:
            if pg.state == PG_REMOVED:
                raise rpc.RpcError("placement group was removed")
            grant = await self._try_grant_pg_lease(pg, cands, demand, conn, p)
            if grant is not None:
                return grant
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not await self._pg_state_wait(
                pg.pg_id, remaining
            ):
                raise rpc.RpcError(
                    f"LEASE_PENDING: waiting for placement-group capacity for "
                    f"{demand.to_dict()} (bundle_index={idx}, state={pg.state})"
                )

    # ---- leases (the scheduling hot path) ------------------------------
    async def rpc_request_lease(self, conn, p):
        """Grant a worker lease: pick node, get a worker from its raylet."""
        demand = ResourceSet(p["resources"])
        strategy = p.get("strategy", {})
        if strategy.get("type") == "placement_group":
            return await self._request_pg_lease(conn, p, demand, strategy)
        actor_id = ActorID(p["actor_id"]) if p.get("actor_id") else None
        if not self.scheduler.is_feasible(demand):
            raise rpc.RpcError(
                f"infeasible resource request {demand.to_dict()}: no node in the "
                f"cluster can ever satisfy it (cluster: "
                f"{[n.resources_total.to_dict() for n in self.nodes.values()]})"
            )
        t_start = time.monotonic()
        deadline = t_start + cfg.sched_max_pending_lease_s
        tag = p.get("tag")
        while True:
            if tag is not None:
                stamp = conn.peer_info.get("cancelled_tags", {}).get(tag)
                if stamp is not None and stamp >= t_start:
                    return {"cancelled": True}
            node = self.scheduler.pick_node(demand, strategy)
            if node is None:
                fut = asyncio.get_running_loop().create_future()
                entry = PendingLease(
                    fut, demand, strategy, conn, actor_id, tag=tag
                )
                self.scheduler.pending.append(entry)
                try:
                    # bounded wait: the client re-requests on LEASE_PENDING so
                    # a vanished client can never leak a queued grant
                    if await asyncio.wait_for(
                        fut, timeout=deadline - time.monotonic()
                    ) == "cancelled":
                        # client demand evaporated (rpc_cancel_lease_requests):
                        # answer with a no-lease marker instead of granting
                        # capacity the client would bounce straight back
                        return {"cancelled": True}
                except asyncio.TimeoutError:
                    # no eager dequeue: membership + remove are O(queue)
                    # on a deque, and with 100k queued the timeout path
                    # IS the hot path.  wait_for already cancelled fut;
                    # _kick_pending lazily drops done/cancelled entries.
                    raise rpc.RpcError(
                        "LEASE_PENDING: waiting for cluster capacity for "
                        f"{demand.to_dict()}"
                    )
                # woken up: re-pick — capacity may have been taken by another
                # grant racing this continuation
                continue
            if not node.resources_available.covers(demand):
                continue  # stale pick; loop re-evaluates
            granted = await self._grant_lease(node, demand, conn, p)
            # chain the drain: kicks wake at most a window of waiters, so
            # a large capacity release (PG removal, node join) relies on
            # each resulting grant re-kicking to keep freed slots filling
            if self.scheduler.pending:
                self._kick_pending()
            return granted

    async def _grant_lease(
        self, node: NodeEntry, demand: ResourceSet, conn, p, pg_ref=None
    ):
        if getattr(conn, "closed", False):
            self._kick_pending()
            raise rpc.RpcError("client disconnected before lease grant")
        lease_id = next(self._lease_ids)
        if pg_ref is not None:
            # PG leases draw from the bundle's reservation, not the node
            # pool (the node pool was already debited at PG creation).
            pg = self.placement_groups[pg_ref[0]]
            pg.bundle_available[pg_ref[1]] = pg.bundle_available[
                pg_ref[1]
            ].subtract(demand)
        else:
            node.resources_available = node.resources_available.subtract(demand)
        node.inflight_grants += 1
        try:
            reply = await node.conn.call(
                "lease_worker",
                {
                    "lease_id": lease_id,
                    "resources": demand.to_dict(),
                    "runtime_env": p.get("runtime_env"),
                },
                timeout=cfg.worker_start_timeout_s,
            )
            # Re-check after the await: _remove_pg may have run while the
            # raylet was starting the worker, and its lease scan could not
            # see this in-flight grant — the reference kills all PG
            # inhabitants on removal, so fail the grant and free the worker.
            if pg_ref is not None:
                pg = self.placement_groups[pg_ref[0]]
                if pg.state == PG_REMOVED or pg.bundle_nodes[pg_ref[1]] != node.node_id:
                    try:
                        await node.conn.notify(
                            "release_worker",
                            {
                                "lease_id": lease_id,
                                "worker_id": reply["worker_id"],
                                "broken": True,
                            },
                        )
                    except Exception:
                        pass
                    # _remove_pg already credited the (post-debit) bundle
                    # remainder back to the node; refund our demand debit
                    # too, or the node leaks capacity permanently
                    if pg.state == PG_REMOVED and node.alive:
                        node.resources_available = (
                            node.resources_available.add(demand)
                        )
                        self._kick_pending()
                    raise rpc.RpcError(
                        "placement group was removed while the lease was "
                        "being granted"
                    )
        except Exception:
            if pg_ref is not None:
                pg = self.placement_groups[pg_ref[0]]
                # refund only if the bundle still lives on this node — it
                # may have been rescheduled elsewhere (already back at full
                # availability) while the lease_worker RPC was in flight
                if (
                    pg.state != PG_REMOVED
                    and pg.bundle_nodes[pg_ref[1]] == node.node_id
                ):
                    pg.bundle_available[pg_ref[1]] = pg.bundle_available[
                        pg_ref[1]
                    ].add(demand)
                    self._wake_pg_waiters(pg.pg_id)
            else:
                node.resources_available = node.resources_available.add(demand)
            self._kick_pending()
            raise
        finally:
            # success continues to the LeaseEntry registration below with
            # no await in between, so a drain's settle poll can never see
            # "no inflight grant AND no lease" for a granted worker
            node.inflight_grants -= 1
        lease = LeaseEntry(
            lease_id=lease_id,
            node_id=node.node_id,
            worker_id=WorkerID(reply["worker_id"]),
            worker_addr=reply["worker_addr"],
            resources=demand,
            client_conn=conn,
            actor_id=ActorID(p["actor_id"]) if p.get("actor_id") else None,
            pg_ref=pg_ref,
        )
        self.leases[lease_id] = lease
        self._conn_leases.setdefault(conn, set()).add(lease_id)
        return {
            "lease_id": lease_id,
            "node_id": node.node_id.hex(),
            "worker_id": reply["worker_id"],
            "worker_addr": reply["worker_addr"],
            "accelerator_env": reply.get("accelerator_env", {}),
        }

    async def rpc_return_lease(self, conn, p):
        await self._release_lease(p["lease_id"], broken=p.get("broken", False))
        return True

    async def rpc_dump_tasks(self, conn, p):
        """Stacks of every live asyncio task in the GCS process — the
        suspended-coroutine complement of dump_worker_stacks (thread
        stacks only show the epoll wait)."""
        def chain(coro, limit=12):
            # follow the await chain (task.get_stack stops at the
            # outermost suspended frame, hiding WHAT it awaits)
            frames = []
            while coro is not None and len(frames) < limit:
                f = getattr(coro, "cr_frame", None) or getattr(
                    coro, "gi_frame", None
                )
                if f is None:
                    frames.append(repr(coro)[:120])
                    break
                frames.append(
                    f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:"
                    f"{f.f_lineno} {f.f_code.co_name}"
                )
                coro = getattr(coro, "cr_await", None) or getattr(
                    coro, "gi_yieldfrom", None
                )
            return frames

        out = []
        for t in asyncio.all_tasks():
            coro = t.get_coro()
            out.append({
                "name": getattr(coro, "__qualname__", str(coro)),
                "stack": chain(coro),
            })
        return out

    async def rpc_cancel_lease_requests(self, conn, p):
        """Cancel THIS client's parked lease requests carrying one of the
        given tags (ray: CancelWorkerLease, raylet node_manager.cc).

        Without this, a client whose task queue drained leaves its parked
        requests behind; every freed slot then ping-pongs through
        grant → client-sees-no-work → return-after-grace, serially
        starving real demand (PGs, new classes) for `grace × parked`
        seconds.  O(pending) walk — acceptable because cancels fire only
        on queue-drain edges, not per task."""
        tags = set(p["tags"])
        # Stamp the cancel on the connection: a request that was mid-wake
        # (granted a re-pick by _kick_pending) is NOT in pending right now
        # but re-parks immediately — it must still observe this cancel, or
        # it ping-pongs forever.  rpc_request_lease checks the stamp
        # against its own start time on every loop iteration.
        stamps = conn.peer_info.setdefault("cancelled_tags", {})
        now = time.monotonic()
        for t in tags:
            stamps[t] = now
        n = 0
        for req in self.scheduler.pending:
            if (
                req.client_conn is conn
                and req.tag in tags
                and not req.fut.done()
            ):
                req.fut.set_result("cancelled")
                n += 1
        return n

    async def _release_lease(self, lease_id: int, broken: bool = False,
                             kick: bool = True):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        self._conn_leases.get(lease.client_conn, set()).discard(lease_id)
        node = self.nodes.get(lease.node_id)
        returned_to_bundle = False
        if lease.pg_ref is not None:
            pg = self.placement_groups.get(lease.pg_ref[0])
            i = lease.pg_ref[1]
            if (
                pg is not None
                and pg.state != PG_REMOVED
                and pg.bundle_nodes[i] == lease.node_id
            ):
                # bundle still lives where the lease ran: capacity returns
                # to the bundle, not the node pool
                pg.bundle_available[i] = pg.bundle_available[i].add(
                    lease.resources
                )
                returned_to_bundle = True
                self._wake_pg_waiters(pg.pg_id)
        if node and node.alive:
            if not returned_to_bundle:
                node.resources_available = node.resources_available.add(
                    lease.resources
                )
            try:
                await node.conn.notify(
                    "release_worker",
                    {
                        "lease_id": lease_id,
                        "worker_id": lease.worker_id.binary(),
                        "broken": broken,
                    },
                )
            except Exception:
                pass
        if kick:
            self._kick_pending()

    def _kick_pending(self):
        """Re-try queued placement groups and lease requests after
        resources freed / node joined.  PGs go first: gang reservations
        are all-or-nothing and would otherwise starve behind a stream of
        small leases."""
        still_pgs: List[PlacementGroupID] = []
        for pg_id in self._pending_pgs:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.state in (PG_CREATED, PG_REMOVED):
                continue
            if not self._try_place_pg(pg):
                still_pgs.append(pg_id)
        self._pending_pgs = still_pgs
        # Bounded scan: each pass pops at most `sched_kick_scan_window`
        # non-placeable requests and wakes at most `window` placeable
        # ones.  The wake bound matters at depth: capacity is only
        # debited when a woken coroutine actually grants, so during this
        # synchronous loop pick_node keeps seeing the same free capacity
        # — unbounded, one freed CPU against a 100k-deep queue would wake
        # ALL 100k waiters (thundering herd, O(backlog) per freed lease).
        # Scanned-but-unplaceable requests ROTATE TO THE TAIL: strict
        # FIFO would let 64 unplaceable requests at the head permanently
        # shadow a placeable one behind them; rotation round-robins the
        # whole queue across kicks instead (lease grant order is not a
        # FIFO contract — and the client-side LEASE_PENDING re-request
        # after sched_max_pending_lease_s is the liveness backstop for
        # any request the rotation visits rarely).  Under-wake after a
        # large capacity release is absorbed by grant-chaining: every
        # successful grant re-kicks while the queue is non-empty.
        pending = self.scheduler.pending
        budget = len(pending)
        fails = 0
        wakes = 0
        window = cfg.sched_kick_scan_window
        while pending and budget > 0 and fails < window and wakes < window:
            budget -= 1
            req = pending.popleft()
            if req.fut.done():
                continue
            if req.client_conn.closed:
                req.fut.cancel()
                continue
            node = self.scheduler.pick_node(req.demand, req.strategy)
            if node is not None:
                req.fut.set_result(True)  # waker only; requester re-picks
                wakes += 1
            else:
                fails += 1
                pending.append(req)  # rotate to tail

    # ---- actors --------------------------------------------------------
    async def rpc_register_actor(self, conn, p):
        actor_id = ActorID(p["actor_id"])
        name = p.get("name")
        ns = p.get("namespace", "default")
        if name:
            key = (ns, name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing and existing.state != ACTOR_DEAD:
                    if p.get("get_if_exists"):
                        return {"existing": True, "actor_id": existing.actor_id.binary()}
                    raise rpc.RpcError(f"actor name {name!r} already taken")
            self.named_actors[key] = actor_id
        # actors created from worker processes have no owning job; they die
        # with the cluster (or explicitly), not with any job
        job_id = JobID(p["job_id"]) if p.get("job_id") else None
        entry = ActorEntry(
            actor_id=actor_id,
            name=name,
            namespace=ns,
            state=ACTOR_PENDING,
            owner_job=job_id,
            max_restarts=p.get("max_restarts", 0),
            creation_spec=p.get("creation_spec"),
            resources=p["resources"],
            scheduling=p.get("strategy", {}),
            runtime_env=p.get("runtime_env"),
            detached=p.get("detached", False),
            on_drain=p.get("on_drain", "migrate"),
            creator_conn=conn,
        )
        self.actors[actor_id] = entry
        # pin ref args inside the creation spec for the actor's lifetime:
        # restart replay must be able to resolve them even after every
        # client ref died
        token = b"actor:" + actor_id.binary()
        for oid in self._spec_ref_oids(entry.creation_spec):
            self.object_holders.setdefault(oid, set()).add(token)
        return {"existing": False, "actor_id": actor_id.binary()}

    @staticmethod
    def _spec_ref_oids(creation_spec) -> List[bytes]:
        out = []
        for item in (creation_spec or {}).get("args", ()):
            if item[0] == "ref":
                out.append(item[1])
            elif item[0] == "kwref":
                out.append(item[2])
        return out

    async def rpc_actor_started(self, conn, p):
        """Creator reports the actor's worker is up and __init__ succeeded."""
        actor = self.actors.get(ActorID(p["actor_id"]))
        if not actor:
            return False
        actor.state = ACTOR_ALIVE
        actor.worker_addr = p["worker_addr"]
        actor.node_id = NodeID.from_hex(p["node_id"])
        actor.lease_id = p.get("lease_id")
        # the actor's lease is now owned by the actor lifetime, not the client
        lease = self.leases.get(actor.lease_id)
        if lease:
            self._conn_leases.get(lease.client_conn, set()).discard(actor.lease_id)
            lease.actor_id = actor.actor_id
        await self.publish(
            f"actor:{actor.actor_id.hex()}",
            {"state": ACTOR_ALIVE, "worker_addr": actor.worker_addr},
        )
        return True

    async def rpc_actor_creation_failed(self, conn, p):
        actor = self.actors.get(ActorID(p["actor_id"]))
        if actor:
            await self._kill_actor(actor, p.get("reason", "creation failed"),
                                   no_restart=True)
        return True

    async def rpc_get_actor(self, conn, p):
        if "name" in p:
            key = (p.get("namespace", "default"), p["name"])
            actor_id = self.named_actors.get(key)
            if actor_id is None:
                return None
            actor = self.actors.get(actor_id)
        else:
            actor = self.actors.get(ActorID(p["actor_id"]))
        if actor is None:
            return None
        # If restarting, optionally wait for the new address
        if actor.state in (ACTOR_PENDING, ACTOR_RESTARTING) and p.get("wait", 0):
            deadline = time.monotonic() + p["wait"]
            while (
                actor.state in (ACTOR_PENDING, ACTOR_RESTARTING)
                and time.monotonic() < deadline
            ):
                await asyncio.sleep(0.05)
        return {
            "actor_id": actor.actor_id.binary(),
            "state": actor.state,
            "worker_addr": actor.worker_addr,
            "node_id": actor.node_id.hex() if actor.node_id else None,
            "name": actor.name,
            "death_cause": actor.death_cause,
            "resources": actor.resources,
        }

    async def rpc_kill_actor(self, conn, p):
        actor = self.actors.get(ActorID(p["actor_id"]))
        if actor:
            await self._kill_actor(
                actor, "killed via ray_tpu.kill", no_restart=p.get("no_restart", True)
            )
        return True

    async def _kill_actor(self, actor: ActorEntry, reason: str, no_restart: bool):
        self._mark_dirty()
        if actor.state == ACTOR_DEAD:
            return
        actor.state = ACTOR_DEAD
        actor.death_cause = reason
        await self._drop_actor_ckpt(actor.actor_id)
        token = b"actor:" + actor.actor_id.binary()
        for oid in self._spec_ref_oids(actor.creation_spec):
            s = self.object_holders.get(oid)
            if s is not None:
                s.discard(token)
                if not s:
                    self._schedule_free(oid)
        if actor.name:
            self.named_actors.pop((actor.namespace, actor.name), None)
        if actor.worker_addr:
            # tell the worker to exit
            wid_conn = None
            lease = self.leases.get(actor.lease_id)
            if lease:
                wid_conn = self._worker_conns.get(lease.worker_id)
            if wid_conn:
                try:
                    await wid_conn.notify("exit_worker", {"reason": reason})
                except Exception:
                    pass
        if actor.lease_id is not None:
            await self._release_lease(actor.lease_id, broken=True)
        await self.publish(
            f"actor:{actor.actor_id.hex()}",
            {"state": ACTOR_DEAD, "death_cause": reason},
        )

    async def _maybe_restart_actor(self, actor: ActorEntry, reason: str):
        if (
            actor.max_restarts != 0
            and (actor.max_restarts < 0 or actor.restarts_used < actor.max_restarts)
            and actor.creation_spec is not None
        ):
            actor.restarts_used += 1
            actor.state = ACTOR_RESTARTING
            actor.worker_addr = None
            self.record_cluster_event(
                "WARNING", "gcs",
                f"actor restarting ({reason})",
                actor_id=actor.actor_id.hex(),
                restarts_used=actor.restarts_used,
            )
            await self.publish(
                f"actor:{actor.actor_id.hex()}", {"state": ACTOR_RESTARTING}
            )
            asyncio.get_running_loop().create_task(self._restart_actor(actor, reason))
        else:
            await self._kill_actor(actor, reason, no_restart=True)

    async def _restart_actor(self, actor: ActorEntry, reason: str):
        self._mark_dirty()
        """GCS-driven actor restart: lease a fresh worker, replay creation."""
        try:
            demand = ResourceSet(actor.resources)
            grant = None
            if actor.scheduling.get("type") == "placement_group":
                # A gang actor restarts into its own bundle (which may
                # itself be rescheduling after the node death).
                pg = self.placement_groups.get(
                    PlacementGroupID.from_hex(actor.scheduling["pg_id"])
                )
                if pg is None:
                    raise rpc.RpcError("actor's placement group not found")
                idx = actor.scheduling.get("bundle_index", -1)
                cands = self._pg_bundle_candidates(pg, idx, demand)
                while grant is None:
                    if pg.state == PG_REMOVED:
                        raise rpc.RpcError(
                            "actor's placement group was removed"
                        )
                    grant = await self._try_grant_pg_lease(
                        pg, cands, demand, _GCS_SELF_CONN,
                        {
                            "actor_id": actor.actor_id.binary(),
                            "runtime_env": getattr(
                                actor, "runtime_env", None
                            ),
                        },
                    )
                    if grant is None:
                        await self._pg_state_wait(pg.pg_id, 5.0)
            else:
                while True:
                    node = self.scheduler.pick_node(demand, actor.scheduling)
                    if node is not None and node.resources_available.covers(
                        demand
                    ):
                        break
                    fut = asyncio.get_running_loop().create_future()
                    self.scheduler.pending.append(
                        PendingLease(fut, demand, actor.scheduling,
                                     actor_id=actor.actor_id,
                                     client_conn=_GCS_SELF_CONN)
                    )
                    await fut
                grant = await self._grant_lease(
                    node, demand, _GCS_SELF_CONN,
                    {
                        "actor_id": actor.actor_id.binary(),
                        "runtime_env": getattr(actor, "runtime_env", None),
                    },
                )
            worker_conn = None
            deadline = time.monotonic() + cfg.worker_start_timeout_s
            wid = WorkerID(grant["worker_id"])
            while time.monotonic() < deadline:
                worker_conn = self._worker_conns.get(wid)
                if worker_conn:
                    break
                await asyncio.sleep(0.02)
            if worker_conn is None:
                raise rpc.RpcError("restarted worker never registered with GCS")
            # graceful-drain handoff: a checkpoint blob (and collective
            # group memberships) parked in the KV rides the creation
            # replay — the worker restores state after __init__
            create_payload = {
                "actor_id": actor.actor_id.binary(),
                "creation_spec": actor.creation_spec,
                "accelerator_env": grant.get("accelerator_env", {}),
            }
            ck_raw = self.kv.get(self._ckpt_key(actor.actor_id))
            if ck_raw is not None:
                import pickle

                try:
                    ck = pickle.loads(ck_raw)
                    create_payload["checkpoint"] = ck.get("blob")
                    create_payload["checkpoint_ref"] = ck.get("blob_ref")
                    create_payload["collective_groups"] = ck.get(
                        "groups") or []
                except Exception:
                    logger.exception("bad actor checkpoint record dropped")
            # No fixed deadline on __init__ replay — liveness comes from the
            # worker: its death breaks the duplex conn and fails this call.
            await worker_conn.call("create_actor", create_payload, timeout=-1)
            await self._drop_actor_ckpt(actor.actor_id)
            actor.state = ACTOR_ALIVE
            actor.worker_addr = grant["worker_addr"]
            actor.node_id = NodeID.from_hex(grant["node_id"])
            actor.lease_id = grant["lease_id"]
            lease = self.leases.get(actor.lease_id)
            if lease:
                lease.actor_id = actor.actor_id
            await self.publish(
                f"actor:{actor.actor_id.hex()}",
                {"state": ACTOR_ALIVE, "worker_addr": actor.worker_addr},
            )
        except Exception as e:
            logger.exception("actor restart failed")
            await self._kill_actor(actor, f"restart failed: {e}", no_restart=True)

    async def rpc_worker_died(self, conn, p):
        """Raylet reports a worker process exited."""
        if p.get("node_id") is not None and p.get("incarnation") is not None:
            # a zombie's death report must not break its replacement's
            # state (notify: swallow instead of raise)
            try:
                self._check_node_fence(
                    NodeID(p["node_id"]), p["incarnation"]
                )
            except FencedError:
                return False
        wid = WorkerID(p["worker_id"])
        # keep a bounded trail of death reasons so drivers can enrich
        # their WorkerCrashedError (e.g. "killed by the memory monitor")
        self._worker_death_reasons[wid.binary()] = p.get("reason") or ""
        while len(self._worker_death_reasons) > 1000:
            self._worker_death_reasons.pop(
                next(iter(self._worker_death_reasons))
            )
        reason = p.get("reason") or ""
        if "memory monitor" in reason:
            self.record_cluster_event(
                "WARNING", "memory_monitor", reason,
                worker_id=wid.hex(),
            )
        self._worker_conns.pop(wid, None)
        self._scrub_holder(wid.binary())
        for lease_id, lease in list(self.leases.items()):
            if lease.worker_id == wid:
                actor_id = lease.actor_id
                await self._release_lease(lease_id, broken=True)
                if actor_id:
                    actor = self.actors.get(actor_id)
                    if actor and actor.state in (ACTOR_ALIVE, ACTOR_PENDING):
                        await self._maybe_restart_actor(
                            actor, f"worker died: {p.get('reason', 'unknown')}"
                        )
        return True

    async def rpc_get_worker_death_info(self, conn, p):
        return {
            "reason": self._worker_death_reasons.get(p["worker_id"], "")
        }

    async def rpc_list_actors(self, conn, p):
        return [
            {
                "actor_id": a.actor_id.hex(),
                "name": a.name,
                "state": a.state,
                "node_id": a.node_id.hex() if a.node_id else None,
                "resources": a.resources,
                "restarts_used": a.restarts_used,
            }
            for a in self.actors.values()
        ]

    async def rpc_node_health(self, conn, p):
        """Health-plane observability: per-node suspicion level, silence,
        and incarnation (what the dashboard/tests/bench read instead of
        groping NodeEntry internals)."""
        now = time.monotonic()
        out = {}
        for nid, n in self.nodes.items():
            det = self.node_health.get(nid)
            out[nid.hex()] = {
                "alive": n.alive and n.conn is not None,
                "suspect": n.suspect,
                "incarnation": n.incarnation,
                "phi": det.phi(now) if det is not None else None,
                "silent_s": now - n.last_heartbeat,
                "mean_interval_s": det.mean() if det is not None else None,
                "samples": len(det._intervals) if det is not None else 0,
            }
        return out

    async def rpc_ping(self, conn, p):
        return {"time": time.time(), "uptime": time.time() - self._start_time}


class _SelfConn:
    """Placeholder 'connection' for GCS-originated leases (actor restarts)."""

    closed = False


_GCS_SELF_CONN: Any = _SelfConn()


# --------------------------------------------------------------------------
# Entrypoint (run as the head's GCS process)
# --------------------------------------------------------------------------


def main():
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--session-dir", default=None,
                    help="enables checkpoint persistence / restart recovery")
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="[gcs] %(levelname)s %(message)s")

    # partition plane: this process IS the control-plane endpoint
    from ray_tpu.common import faults as _faults

    _faults.set_local_endpoint("gcs")

    # SIGUSR1 → dump all thread stacks to stderr (the gcs log): the
    # zero-dependency "where is it stuck" probe
    import faulthandler
    import signal as _sig

    faulthandler.register(_sig.SIGUSR1)

    prof_dir = os.environ.get("RT_PROFILE_DIR")
    if prof_dir:
        # dev profiling (see util/profiling.py): capture the whole server
        # loop; SIGTERM (the normal teardown signal) dumps the stats
        import cProfile
        import signal

        prof = cProfile.Profile()
        path = os.path.join(prof_dir, f"gcs-{os.getpid()}.pstats")

        def _term(_sig, _frm):
            prof.disable()
            prof.dump_stats(path)
            sys.exit(0)

        signal.signal(signal.SIGTERM, _term)
        prof.enable()

    async def run():
        gcs = GcsServer(
            host=args.host, port=args.port, session_dir=args.session_dir
        )
        await gcs.start()
        # report the bound address to the parent on stdout
        print(f"GCS_ADDRESS={gcs.address}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
