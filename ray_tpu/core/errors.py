"""User-facing exception types.

Role-equivalent of ray: python/ray/exceptions.py (RayTaskError,
RayActorError, ObjectLostError, ...).
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception remotely; re-raised at `get`.

    Carries the remote traceback string so the user sees where it failed.
    """

    def __init__(self, cause_type: str, cause_msg: str, remote_tb: str,
                 task_desc: str = ""):
        self.cause_type = cause_type
        self.cause_msg = cause_msg
        self.remote_tb = remote_tb
        self.task_desc = task_desc
        super().__init__(
            f"{task_desc or 'task'} failed with {cause_type}: {cause_msg}\n"
            f"--- remote traceback ---\n{remote_tb}"
        )

    def __reduce__(self):
        return (
            TaskError,
            (self.cause_type, self.cause_msg, self.remote_tb, self.task_desc),
        )

    @classmethod
    def from_exception(cls, e: Exception, task_desc: str = "") -> "TaskError":
        return cls(
            type(e).__name__,
            str(e),
            "".join(traceback.format_exception(type(e), e, e.__traceback__)),
            task_desc,
        )


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayTpuError):
    """Actor task cannot run: the actor is dead or dying."""

    def __init__(self, msg: str, actor_id=None):
        super().__init__(msg)
        self.actor_id = actor_id


class ActorDiedError(ActorError):
    pass


class ObjectLostError(RayTpuError):
    """Object's value was lost from the cluster and could not be recovered."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class FencedError(RayTpuError):
    """The sender's node incarnation is stale: the cluster declared that
    node dead (and bumped its incarnation), so RPCs from the old life
    are rejected.  A raylet receiving this must fence itself — kill its
    workers, discard its object copies and spill files, and re-register
    fresh — closing the split-brain window a healed partition opens
    (two live copies of a named actor, stale lease grants
    double-executing tasks)."""


def is_fenced(exc: BaseException) -> bool:
    """True when ``exc`` is a FencedError, locally raised or carried
    inside an rpc.RemoteCallError from a peer's fence check."""
    if isinstance(exc, FencedError):
        return True
    remote = getattr(exc, "remote_exception", None)
    return isinstance(remote, FencedError)
