"""TPU-pod NodeProvider: slice-granular provisioning against the GCE TPU
API.

Role-equivalent of ray: python/ray/autoscaler/_private/gcp/node_provider.py:63
reshaped for TPU reality: the provisioning unit is a SLICE (all hosts of
a v5e-16, v4-32, ...), not a VM.  One ``create_node`` call asks the TPU
API for a queued resource; when the slice is READY every host runs a
raylet with the slice env injected (``TPU_NAME``, ``TPU_WORKER_ID``,
``TPU_WORKER_HOSTNAMES``, ``TPU_ACCELERATOR_TYPE``), which is exactly
what `accelerators/tpu.py` turns into the ``<slice>`` gang resource and
the ``TPU-<slice>-head`` coordinator resource.

The API client is injectable: ``FakeGceTpuApi`` (default here — this
environment has no egress) keeps slice state in memory and "boots" hosts
as local raylet subprocesses, so the autoscaler e2e path — demand →
create slice → hosts register → gang schedulable → idle → drain —
exercises the same lifecycle a real deployment has, with only the REST
transport faked.
"""

from __future__ import annotations

import logging
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider, ProviderNode

logger = logging.getLogger(__name__)

#: accelerator_type -> (n_hosts, chips_per_host, generation)
SLICE_SHAPES: Dict[str, tuple] = {
    "v5litepod-4": (1, 4, "v5e"),
    "v5litepod-8": (2, 4, "v5e"),
    "v5litepod-16": (4, 4, "v5e"),
    "v5litepod-32": (8, 4, "v5e"),
    "v4-8": (1, 4, "v4"),
    "v4-16": (2, 4, "v4"),
    "v4-32": (4, 4, "v4"),
    "v6e-8": (2, 4, "v6e"),
    "v6e-16": (4, 4, "v6e"),
}


def slice_shape(accelerator_type: str) -> tuple:
    try:
        return SLICE_SHAPES[accelerator_type]
    except KeyError:
        raise ValueError(
            f"unknown accelerator_type {accelerator_type!r}; known: "
            f"{sorted(SLICE_SHAPES)}"
        ) from None


@dataclass
class TpuSlice:
    name: str
    accelerator_type: str
    state: str = "CREATING"  # CREATING -> READY -> DELETING
    endpoints: List[str] = field(default_factory=list)
    meta: dict = field(default_factory=dict)


class GceTpuApi:
    """Transport interface to the TPU control plane (tpu.googleapis.com
    v2 nodes/queuedResources).  The real implementation is a thin REST
    client configured with project/zone credentials; it is deliberately
    not baked in here (no egress in CI) — deployments subclass or inject
    their own."""

    def create_slice(self, name: str, accelerator_type: str) -> TpuSlice:
        raise NotImplementedError

    def delete_slice(self, name: str) -> None:
        raise NotImplementedError

    def get_slice(self, name: str) -> Optional[TpuSlice]:
        raise NotImplementedError

    def list_slices(self) -> List[TpuSlice]:
        raise NotImplementedError


class FakeGceTpuApi(GceTpuApi):
    """In-memory TPU control plane: slices become READY immediately with
    one fake endpoint per host."""

    def __init__(self):
        self._slices: Dict[str, TpuSlice] = {}
        self._lock = threading.Lock()

    def create_slice(self, name, accelerator_type) -> TpuSlice:
        n_hosts, _, _ = slice_shape(accelerator_type)
        with self._lock:
            if name in self._slices:
                raise ValueError(f"slice {name!r} already exists")
            s = TpuSlice(
                name=name,
                accelerator_type=accelerator_type,
                state="READY",
                endpoints=[f"10.0.0.{i + 1}:8470" for i in range(n_hosts)],
            )
            self._slices[name] = s
            return s

    def delete_slice(self, name) -> None:
        with self._lock:
            self._slices.pop(name, None)

    def get_slice(self, name) -> Optional[TpuSlice]:
        with self._lock:
            return self._slices.get(name)

    def list_slices(self) -> List[TpuSlice]:
        with self._lock:
            return list(self._slices.values())


class GceMetadataPreemption:
    """GCE metadata-server preemption poll (the raylet's watcher source).

    A preemptible/spot TPU VM learns of its termination via the metadata
    server's ``instance/preempted`` flag (and an ACPI G2 signal) roughly
    30 s before the kill.  ``poll()`` returns the announced drain budget
    in seconds when the flag is TRUE, else 0.  The HTTP fetch is
    injectable so tests (and this egress-less environment) drive it with
    a fake; the raylet enables the real poll with ``RT_PREEMPT_METADATA``.
    """

    URL = (
        "http://metadata.google.internal/computeMetadata/v1/"
        "instance/preempted"
    )
    #: what GCE actually grants between notice and kill
    DEFAULT_DEADLINE_S = 30.0

    def __init__(self, fetch=None, deadline_s: Optional[float] = None):
        self._fetch = fetch or self._http_fetch
        self.deadline_s = (
            deadline_s if deadline_s is not None else self.DEFAULT_DEADLINE_S
        )

    def _http_fetch(self) -> str:
        import urllib.request

        req = urllib.request.Request(
            self.URL, headers={"Metadata-Flavor": "Google"}
        )
        try:
            with urllib.request.urlopen(req, timeout=1.0) as resp:
                return resp.read().decode("utf-8", "replace").strip()
        except Exception:
            return "FALSE"  # no metadata server / transient: not preempted

    def poll(self) -> float:
        """Seconds of drain budget if preempted, else 0."""
        try:
            flag = self._fetch()
        except Exception:
            return 0.0
        return self.deadline_s if str(flag).upper() == "TRUE" else 0.0


class TpuPodProvider(NodeProvider):
    """Slice-granular provider: create_node provisions a whole TPU slice
    and boots a raylet per host with the slice env injected."""

    def __init__(
        self,
        gcs_address: str,
        session_dir: str,
        api: Optional[GceTpuApi] = None,
        cpus_per_host: float = 4.0,
        slice_ready_timeout_s: float = 1800.0,
        poll_interval_s: float = 5.0,
    ):
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.api = api or FakeGceTpuApi()
        self.cpus_per_host = cpus_per_host
        self.slice_ready_timeout_s = slice_ready_timeout_s
        self.poll_interval_s = poll_interval_s
        self._nodes: Dict[str, ProviderNode] = {}
        self._counter = 0
        self._lock = threading.Lock()

    def _wait_ready(self, tpu: TpuSlice) -> TpuSlice:
        """Poll until the slice is READY (queued resources sit in
        WAITING_FOR_RESOURCES/PROVISIONING for minutes on the real API;
        the fake answers READY immediately).  FAILED or timeout tears
        the queued resource down — a half-born slice must not leak."""
        import time

        deadline = time.monotonic() + self.slice_ready_timeout_s
        cur = tpu
        while cur.state != "READY":
            if cur.state == "FAILED":
                self.api.delete_slice(tpu.name)
                raise RuntimeError(
                    f"TPU slice {tpu.name} failed to provision: "
                    f"{cur.meta}"
                )
            if time.monotonic() > deadline:
                self.api.delete_slice(tpu.name)
                raise TimeoutError(
                    f"TPU slice {tpu.name} not READY within "
                    f"{self.slice_ready_timeout_s:.0f}s (last state "
                    f"{cur.state}, {cur.meta})"
                )
            time.sleep(self.poll_interval_s)
            nxt = self.api.get_slice(tpu.name)
            if nxt is None:
                raise RuntimeError(
                    f"TPU slice {tpu.name} vanished while provisioning"
                )
            cur = nxt
        return cur

    def _host_resources(
        self, slice_name: str, worker_id: int, accelerator_type: str
    ) -> Dict[str, float]:
        """What accelerators/tpu.py would detect on this host (explicit
        here because the fake hosts are plain subprocesses)."""
        _, chips, gen = slice_shape(accelerator_type)
        out = {
            "CPU": self.cpus_per_host,
            "TPU": float(chips),
            f"TPU-{gen}": float(chips),
            slice_name: 1.0,
        }
        if worker_id == 0:
            out[f"TPU-{slice_name}-head"] = 1.0
        return out

    def create_node(self, node_type, resources, labels) -> ProviderNode:
        """node_type must be an accelerator_type key (e.g. v5litepod-16);
        `resources` describe ONE HOST and are merged over the detected
        slice resources."""
        from ray_tpu.core import node as node_mod

        with self._lock:
            self._counter += 1
            slice_name = f"rt-{node_type}-{self._counter}"
        tpu = self._wait_ready(self.api.create_slice(slice_name, node_type))
        n_hosts, chips, _gen = slice_shape(node_type)
        procs: List[subprocess.Popen] = []
        node_ids: List[str] = []
        hostnames = ",".join(e.split(":")[0] for e in tpu.endpoints)
        try:
            for worker_id in range(n_hosts):
                host_res = self._host_resources(
                    slice_name, worker_id, node_type
                )
                host_res.update(resources or {})
                host_labels = dict(labels or {})
                host_labels.update({
                    "ray_tpu.node_type": node_type,
                    "ray_tpu.slice": slice_name,
                    "ray_tpu.tpu_worker_id": str(worker_id),
                })
                proc, _addr, nid, _store = node_mod.start_raylet(
                    self.gcs_address,
                    self.session_dir,
                    host_res,
                    labels=host_labels,
                    extra_env={
                        "TPU_NAME": slice_name,
                        "TPU_WORKER_ID": str(worker_id),
                        "TPU_WORKER_HOSTNAMES": hostnames,
                        "TPU_ACCELERATOR_TYPE": node_type,
                    },
                )
                procs.append(proc)
                node_ids.append(nid)
        except BaseException:
            for p in procs:
                p.terminate()
            self.api.delete_slice(slice_name)
            raise
        pn = ProviderNode(
            provider_id=slice_name,
            node_type=node_type,
            node_id_hex=node_ids[0],
            proc=procs[0],
            meta={"procs": procs, "node_ids": node_ids,
                  "endpoints": tpu.endpoints},
        )
        with self._lock:
            self._nodes[slice_name] = pn
        logger.info(
            "provisioned TPU slice %s (%s: %d hosts x %d chips)",
            slice_name, node_type, n_hosts, chips,
        )
        return pn

    def terminate_node(self, node: ProviderNode) -> None:
        with self._lock:
            self._nodes.pop(node.provider_id, None)
        for p in node.meta.get("procs", []):
            if p.poll() is None:
                p.terminate()
        for p in node.meta.get("procs", []):
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        self.api.delete_slice(node.provider_id)
        logger.info("terminated TPU slice %s", node.provider_id)

    def non_terminated_nodes(self) -> List[ProviderNode]:
        with self._lock:
            out = []
            for pn in list(self._nodes.values()):
                procs = pn.meta.get("procs", [])
                if procs and all(p.poll() is not None for p in procs):
                    # every host died out of band: the slice is gone
                    del self._nodes[pn.provider_id]
                    self.api.delete_slice(pn.provider_id)
                else:
                    out.append(pn)
            return out