"""The reconcile loop: pending demand -> node launches, idle -> drains.

Role-equivalent of the reference's autoscaler v2 scheduler + reconciler
(ray: python/ray/autoscaler/v2/scheduler.py:624 ResourceDemandScheduler,
instance_manager/reconciler.py:53) in one deliberate pass:

    demand  = pending leases + unplaced PG bundles       (from the GCS)
    supply  = running nodes + launches still registering
    plan    = first-fit-decreasing bin-pack of unmet demand onto the
              cheapest node types that fit (STRICT_PACK bundles must
              land whole on one node — a slice shape)
    action  = launch plan nodes; drain nodes idle > idle_timeout over
              their type's min_workers

TPU framing: a node type IS a slice shape ({"TPU": 4, "CPU": 8},
label generation=v5e), so "scale up for this STRICT_PACK PG" means
"allocate another slice of that shape".
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.common.resources import ResourceSet
from ray_tpu.core import rpc

logger = logging.getLogger(__name__)


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 100
    labels: Dict[str, str] = field(default_factory=dict)
    #: relative $/node-second — the launch planner prefers the cheaper
    #: of two types that both fit a demand (spot-fleet economics)
    price: float = 1.0
    #: preemptible capacity: the provider may revoke it with a notice
    #: (soak.spot drives the seeded revocation process); the fleet's
    #: answer to churn is the drain plane + min_workers replacement
    preemptible: bool = False


@dataclass
class AutoscalerConfig:
    node_types: List[NodeTypeConfig]
    idle_timeout_s: float = 60.0
    interval_s: float = 1.0
    max_launch_batch: int = 8
    # graceful idle-drain budget: the GCS evacuates sole-copy objects
    # (an "idle" node holds no leases/actors but may still hold the only
    # copy of live objects) before the provider terminates the node
    idle_drain_deadline_s: float = 15.0


class Autoscaler:
    """Drives a NodeProvider from GCS demand.  Run via `start()` inside
    an asyncio loop (the monitor process) or step manually with
    `reconcile()` (tests)."""

    def __init__(self, gcs_address: str, provider, config: AutoscalerConfig):
        self.gcs_address = gcs_address
        self.provider = provider
        self.config = config
        self.gcs: Optional[rpc.ReconnectingConnection] = None
        self._idle_since: Dict[str, float] = {}  # node_id_hex -> ts
        # drain-then-terminate in flight: provider_id -> (pn, nids,
        # settle deadline).  Checked once per reconcile pass instead of
        # blocking the single reconcile coroutine for the whole drain.
        self._pending_terminations: Dict[str, tuple] = {}
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    async def start(self):
        self.gcs = rpc.ReconnectingConnection(
            self.gcs_address, name="autoscaler->gcs"
        )
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self):
        self._stopped = True
        if self._task:
            self._task.cancel()
        if self.gcs:
            await self.gcs.close()

    async def _loop(self):
        while not self._stopped:
            try:
                await self.reconcile()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("autoscaler reconcile failed")
            await asyncio.sleep(self.config.interval_s)

    # ---- the pass ------------------------------------------------------

    async def reconcile(self):
        state = await self.gcs.call("get_autoscaler_state", {})
        demands = self._unmet_demands(state)
        launches = self._plan_launches(demands, state)
        for node_type in launches:
            tc = self._type(node_type)
            # provider CRUD is blocking by contract (a real cloud API
            # polls a queued resource to READY for minutes) — it must
            # never run on the monitor's event loop
            await asyncio.to_thread(
                self.provider.create_node, node_type, tc.resources,
                tc.labels,
            )
        await self._drain_idle(state)
        await self._reap_drained()

    def _type(self, name: str) -> NodeTypeConfig:
        for tc in self.config.node_types:
            if tc.name == name:
                return tc
        raise KeyError(name)

    def _unmet_demands(self, state) -> List[ResourceSet]:
        """Demand that existing capacity cannot absorb.

        PG bundles are gang demand: each unplaced bundle is one unit that
        must fit whole on some node (matches the GCS's per-bundle atomic
        placement).  Pending leases are singles.
        """
        # draining nodes (idle teardown or a preemption notice) are not
        # supply: counting them would suppress the replacement launch
        # that proactive evacuation needs capacity for.  SUSPECT nodes
        # (health plane) DO count: the scheduler merely deprioritizes
        # them, so their queued demand is transient — launching
        # replacement capacity for every load stall would turn each
        # suspicion into a billable scale-up/scale-down flap
        free = [
            ResourceSet(n["resources_available"])
            for n in state["nodes"]
            if n["alive"] and not n.get("draining")
        ]
        # launches still registering count as supply, or every reconcile
        # pass while a node boots would launch another copy
        registered = {n["node_id"] for n in state["nodes"] if n["alive"]}
        for pn in self.provider.non_terminated_nodes():
            if pn.node_id_hex not in registered:
                free.append(ResourceSet(self._type(pn.node_type).resources))
        unmet: List[ResourceSet] = []

        def absorb(demand: ResourceSet) -> bool:
            for i, f in enumerate(free):
                if f.covers(demand):
                    free[i] = f.subtract(demand)
                    return True
            return False

        units: List[ResourceSet] = []
        for pl in state["pending_leases"]:
            units.append(ResourceSet(pl["demand"]))
        for pgb in state["pending_pg_bundles"]:
            units.extend(ResourceSet(b) for b in pgb["bundles"])
        # big first: gang bundles should claim fresh nodes before smalls
        units.sort(key=lambda r: -sum(r.to_dict().values()))
        for d in units:
            if not absorb(d):
                unmet.append(d)
        return unmet

    def _plan_launches(self, unmet: List[ResourceSet], state) -> List[str]:
        """First-fit-decreasing onto the smallest node type that fits."""
        if not unmet:
            return self._min_workers_topup(state)
        counts = self._current_counts(state, exclude_draining=True)
        plan: List[str] = []
        # virtual free pools of nodes we are about to launch
        virtual: List[ResourceSet] = []

        # smallest that fits, and among equal sizes the CHEAPER type —
        # with a discounted preemptible type configured this is the
        # spot-fleet bet: provision cheap churny capacity and let the
        # drain plane + min_workers replacement absorb the revocations
        types_small_first = sorted(
            self.config.node_types,
            key=lambda t: (sum(t.resources.values()), t.price),
        )
        for d in unmet:
            placed = False
            for i, f in enumerate(virtual):
                if f.covers(d):
                    virtual[i] = f.subtract(d)
                    placed = True
                    break
            if placed:
                continue
            for tc in types_small_first:
                full = ResourceSet(tc.resources)
                if not full.covers(d):
                    continue
                if counts.get(tc.name, 0) >= tc.max_workers:
                    continue
                if len(plan) >= self.config.max_launch_batch:
                    break
                plan.append(tc.name)
                counts[tc.name] = counts.get(tc.name, 0) + 1
                virtual.append(full.subtract(d))
                placed = True
                break
            if not placed and not any(
                ResourceSet(t.resources).covers(d)
                for t in self.config.node_types
            ):
                logger.warning(
                    "demand %s fits no configured node type", d.to_dict()
                )
        return plan + self._min_workers_topup(state, counts)

    def _min_workers_topup(self, state, counts=None) -> List[str]:
        if counts is None:
            counts = self._current_counts(state, exclude_draining=True)
        plan = []
        for tc in self.config.node_types:
            have = counts.get(tc.name, 0)
            for _ in range(max(0, tc.min_workers - have)):
                plan.append(tc.name)
                counts[tc.name] = counts.get(tc.name, 0) + 1
        return plan

    def _current_counts(self, state=None,
                        exclude_draining: bool = False) -> Dict[str, int]:
        """Provider-side node counts by type.  ``exclude_draining``
        drops nodes the GCS reports mid-drain — a preemption-noticed
        node is walking dead, and counting it would suppress the
        replacement launch until AFTER the kill (a full blackout of
        provisioning latency instead of an overlap).  Idle drains never
        flap under this: they only start while counts exceed
        min_workers, so the excluded victim still leaves >= min."""
        draining = set()
        if exclude_draining and state is not None:
            draining = {
                n["node_id"] for n in state["nodes"]
                if n["alive"] and n.get("draining")
            }
        counts: Dict[str, int] = {}
        for pn in self.provider.non_terminated_nodes():
            nids = pn.meta.get("node_ids") or [pn.node_id_hex]
            if draining and all(nid in draining for nid in nids):
                continue
            counts[pn.node_type] = counts.get(pn.node_type, 0) + 1
        return counts

    async def _drain_idle(self, state):
        now = time.monotonic()
        idle_ids = set()
        for n in state["nodes"]:
            if not n["alive"]:
                continue
            if n.get("suspect"):
                # a failure-suspected node is unreachable-ish right now:
                # its idle drain would stall on the evacuation pulls and
                # fall back to a hard kill — let the health plane decide
                # its fate first (the idle clock also resets: suspicion
                # usually means the idleness read is stale)
                self._idle_since.pop(n["node_id"], None)
                continue
            if n["idle"]:
                idle_ids.add(n["node_id"])
                self._idle_since.setdefault(n["node_id"], now)
            else:
                self._idle_since.pop(n["node_id"], None)
        # drop tracking for nodes that disappeared
        for nid in list(self._idle_since):
            if nid not in idle_ids:
                self._idle_since.pop(nid)
        counts = self._current_counts(state)
        dead_ids = {
            n["node_id"] for n in state["nodes"] if not n["alive"]
        }
        for pn in self.provider.non_terminated_nodes():
            nids = pn.meta.get("node_ids") or [pn.node_id_hex]
            if len(nids) > 1 and any(nid in dead_ids for nid in nids):
                # a partially-dead slice can never serve its gang
                # resource again: replace it instead of holding it
                # (billed, counted, unschedulable) forever
                logger.warning(
                    "terminating broken slice %s: host(s) dead",
                    pn.provider_id,
                )
                await asyncio.to_thread(self.provider.terminate_node, pn)
                counts[pn.node_type] = counts.get(pn.node_type, 1) - 1
                for nid in nids:
                    self._idle_since.pop(nid, None)
                continue
            # a multi-host provider node (TPU slice) drains only when
            # EVERY host has been idle past the timeout — a gang resource
            # with one busy host is a busy slice
            sinces = [self._idle_since.get(x) for x in nids]
            if any(
                s is None or now - s < self.config.idle_timeout_s
                for s in sinces
            ):
                continue
            tc = self._type(pn.node_type)
            # nodes queued for termination still show in provider counts
            # until their drain settles — subtract them, or successive
            # passes drain one node per tick straight through min_workers
            pending_same_type = sum(
                1 for (ppn, _n, _d) in self._pending_terminations.values()
                if ppn.node_type == pn.node_type
            )
            if counts.get(pn.node_type, 0) - pending_same_type <= tc.min_workers:
                continue
            if pn.provider_id in self._pending_terminations:
                continue  # drain already in flight
            logger.info(
                "draining idle node %s (%s)", pn.provider_id, pn.node_type
            )
            # deadline-based graceful drain: the GCS evacuates sole-copy
            # objects off the node inside the budget; termination happens
            # on a LATER reconcile pass once every host's drain settles
            # (drained/failed) or the budget lapses — blocking here would
            # stall scale-up for the whole drain (the hard node-death
            # fallback covers whatever the drain did not finish)
            budget = self.config.idle_drain_deadline_s
            for nid in nids:
                try:
                    await self.gcs.call(
                        "drain_node",
                        {"node_id": nid, "reason": "idle",
                         "deadline_s": budget},
                    )
                except Exception:
                    logger.exception("drain_node rpc failed")
            self._pending_terminations[pn.provider_id] = (
                pn, nids, time.monotonic() + budget + 1.0
            )
            counts[pn.node_type] -= 1
            for nid in nids:
                self._idle_since.pop(nid, None)

    async def _reap_drained(self):
        """Terminate drain-then-stop victims whose drain settled (or
        whose settle deadline lapsed).  One non-blocking status check per
        reconcile pass."""
        settled_states = ("drained", "failed", "dead", "none", "unknown")
        for pid, (pn, nids, deadline) in list(
            self._pending_terminations.items()
        ):
            if time.monotonic() < deadline:
                try:
                    states = [
                        (await self.gcs.call(
                            "get_drain_status", {"node_id": nid}
                        ) or {}).get("state")
                        for nid in nids
                    ]
                except Exception:
                    continue  # GCS hiccup: re-check next pass
                if not all(s in settled_states for s in states):
                    continue  # still draining inside the budget
            del self._pending_terminations[pid]
            await asyncio.to_thread(self.provider.terminate_node, pn)


def main():
    """Monitor entrypoint: `python -m ray_tpu.autoscaler.autoscaler
    --gcs HOST:PORT --node-type name=CPU:4,TPU:0:min=0:max=10 ...`"""
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--gcs", required=True)
    ap.add_argument("--session-dir", required=True)
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--idle-timeout", type=float, default=60.0)
    ap.add_argument(
        "--node-type", action="append", default=[],
        help='"name=v5e_slice4;resources=CPU:8,TPU:4;min=0;max=8"',
    )
    ap.add_argument(
        "--cluster-config", default=None,
        help="cluster.yaml (ray_tpu up): node types AND provider come "
             "from the file; --node-type is ignored",
    )
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="[autoscaler] %(levelname)s %(message)s")

    if args.cluster_config:
        from ray_tpu.autoscaler import launcher

        ccfg = launcher.load_cluster_config(args.cluster_config)
        node_types = launcher.node_type_configs(ccfg)
        provider = launcher.build_provider(
            ccfg, args.gcs, args.session_dir
        )
    else:
        node_types = []
        for spec in args.node_type:
            fields = dict(f.split("=", 1) for f in spec.split(";"))
            resources = {
                k: float(v)
                for k, v in (
                    kv.split(":") for kv in fields["resources"].split(",")
                )
            }
            node_types.append(
                NodeTypeConfig(
                    fields["name"],
                    resources,
                    int(fields.get("min", 0)),
                    int(fields.get("max", 100)),
                    price=float(fields.get("price", 1.0)),
                    preemptible=fields.get(
                        "preemptible", "false"
                    ).lower() in ("1", "true", "yes"),
                )
            )

        from ray_tpu.autoscaler.node_provider import LocalSubprocessProvider

        provider = LocalSubprocessProvider(args.gcs, args.session_dir)
    cfg = AutoscalerConfig(
        node_types=node_types,
        idle_timeout_s=args.idle_timeout,
        interval_s=args.interval,
    )

    async def run():
        a = Autoscaler(args.gcs, provider, cfg)
        await a.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
