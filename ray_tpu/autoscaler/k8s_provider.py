"""Kubernetes node provider: declarative scaling through a cluster CRD.

Role-equivalent of the reference's KubeRay integration (ray:
python/ray/autoscaler/batching_node_provider.py — scale via one
declarative patch per reconcile batch, never imperative instance CRUD —
plus python/ray/autoscaler/kuberay/): the autoscaler expresses "this
group should have N workers, minus these specific pods" by patching an
``RtCluster`` custom resource; an in-cluster operator owns the pod
lifecycle.  TPU framing: a worker group is a SLICE SHAPE (every pod of
a group mounts the same accelerator topology), so gang semantics live
in the group, exactly like TpuPodProvider's slices.

The CRD shape this provider reads/writes::

    apiVersion: ray-tpu.io/v1
    kind: RtCluster
    metadata: {name, namespace}
    spec:
      workerGroups:
        - name: v5e-4            # == autoscaler node_type
          replicas: 2
          workersToDelete: []    # pod names pending scale-down
          template: {...}        # operator-owned pod template

Pods carry labels ``ray-tpu.io/cluster`` and ``ray-tpu.io/group`` and
an annotation ``ray-tpu.io/node-id`` (set by the raylet once attached)
so provider pods can be matched to GCS nodes.

Transport is ``KubeApi``: the real ``RestKubeApi`` speaks the k8s REST
API with in-cluster service-account auth; tests run it byte-for-byte
against a local fixture server (no egress), mirroring how
``RestGceTpuApi`` is tested.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider, ProviderNode

logger = logging.getLogger(__name__)

GROUP = "ray-tpu.io"
VERSION = "v1"
PLURAL = "rtclusters"

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(RuntimeError):
    def __init__(self, status: int, method: str, path: str, body: str):
        self.status = status
        super().__init__(f"{method} {path} -> HTTP {status}: {body[:500]}")


class KubeApi:
    """Minimal transport the provider needs.  ``patch`` is a JSON merge
    patch (RFC 7386) — the declarative write primitive."""

    def get(self, path: str) -> dict:
        raise NotImplementedError

    def patch(self, path: str, body: dict) -> dict:
        raise NotImplementedError


class RestKubeApi(KubeApi):
    """In-cluster k8s REST client: bearer token + CA from the mounted
    service account (the operator deployment path), or injected
    ``base_url``/``token_fn`` (fixture tests, kubeconfig wrappers)."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token_fn: Optional[Callable[[], str]] = None,
        ca_file: Optional[str] = None,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in a kubernetes pod (KUBERNETES_SERVICE_HOST "
                    "unset) and no base_url injected"
                )
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        self._token_fn = token_fn
        if self.base_url.startswith("https://"):
            sa_ca = os.path.join(_SA_DIR, "ca.crt")
            if ca_file is not None:
                self._ssl = ssl.create_default_context(cafile=ca_file)
            elif os.path.exists(sa_ca):  # in-cluster: the mounted CA
                self._ssl = ssl.create_default_context(cafile=sa_ca)
            else:  # off-cluster https (kubeconfig wrapper): system CAs
                self._ssl = ssl.create_default_context()
        else:  # http fixture server in tests
            self._ssl = None

    def _token(self) -> str:
        if self._token_fn is not None:
            return self._token_fn()
        with open(os.path.join(_SA_DIR, "token")) as f:
            return f.read().strip()

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json"):
        url = self.base_url + path
        data = None
        headers = {
            "Authorization": f"Bearer {self._token()}",
            "Accept": "application/json",
        }
        if body is not None:
            data = json.dumps(body, sort_keys=True).encode()
            headers["Content-Type"] = content_type
        req = urllib.request.Request(
            url, data=data, headers=headers, method=method
        )
        try:
            with urllib.request.urlopen(
                req, timeout=60, context=self._ssl
            ) as r:
                payload = r.read()
        except urllib.error.HTTPError as e:
            raise KubeApiError(
                e.code, method, path, e.read().decode(errors="replace")
            ) from None
        return json.loads(payload) if payload else {}

    def get(self, path: str) -> dict:
        return self._request("GET", path)

    def patch(self, path: str, body: dict) -> dict:
        # JSON merge patch: replaces exactly the named fields — the
        # provider sends the whole workerGroups array in one write
        return self._request(
            "PATCH", path, body, content_type="application/merge-patch+json"
        )


def cr_path(namespace: str, name: str) -> str:
    return (
        f"/apis/{GROUP}/{VERSION}/namespaces/{namespace}/{PLURAL}/{name}"
    )


def pods_path(namespace: str, cluster: str) -> str:
    sel = urllib.parse.quote(f"{GROUP}/cluster={cluster}")
    return f"/api/v1/namespaces/{namespace}/pods?labelSelector={sel}"


class KubeRayProvider(NodeProvider):
    """Scale worker groups of an RtCluster CR declaratively.

    Unlike the subprocess/TPU providers, nodes are not born from
    ``create_node`` — the operator materializes pods after a replicas
    patch.  ``create_node`` therefore returns a PENDING placeholder
    (no node_id yet), which the autoscaler already treats as
    capacity-in-flight; ``non_terminated_nodes`` reports live pods
    plus one placeholder per not-yet-manifested replica.
    """

    def __init__(self, api: KubeApi, namespace: str, cluster_name: str):
        self.api = api
        self.namespace = namespace
        self.cluster_name = cluster_name
        self._lock = threading.Lock()

    # -- CR access -------------------------------------------------------
    def _get_cr(self) -> dict:
        return self.api.get(cr_path(self.namespace, self.cluster_name))

    def _groups(self, cr: dict) -> List[dict]:
        return (cr.get("spec") or {}).get("workerGroups") or []

    def _patch_groups(self, cr: dict, groups: List[dict]) -> None:
        # Optimistic concurrency: echo the read CR's resourceVersion so
        # the apiserver rejects (409) a write that would clobber a
        # concurrent writer's update (e.g. the operator consuming a
        # workersToDelete entry between our read and patch).
        body: dict = {"spec": {"workerGroups": groups}}
        rv = (cr.get("metadata") or {}).get("resourceVersion")
        if rv is not None:
            body["metadata"] = {"resourceVersion": rv}
        self.api.patch(cr_path(self.namespace, self.cluster_name), body)

    def _mutate_groups(self, mutate) -> Optional[dict]:
        """get → ``mutate(groups)`` → patch, retrying the whole
        read-modify-write on 409 conflict.  ``mutate`` returns the
        touched group dict, or None to abort (no patch sent).

        The provider lock covers each ATTEMPT, not the backoff sleeps —
        every attempt re-reads the CR anyway, so correctness is per-RMW,
        and sleeping under the lock would convoy concurrent scale ops
        behind one retry storm for seconds."""
        last: Optional[KubeApiError] = None
        for attempt in range(8):
            with self._lock:
                cr = self._get_cr()
                groups = self._groups(cr)
                g = mutate(groups)
                if g is None:
                    return None
                try:
                    self._patch_groups(cr, groups)
                    return g
                except KubeApiError as e:
                    if e.status != 409:
                        raise
                    last = e  # stale resourceVersion: re-read and retry
            # any CR write (operator status updates included) bumps
            # resourceVersion; back off so a reconcile storm can't
            # exhaust back-to-back retries
            time.sleep(min(0.05 * (2 ** attempt), 1.0))
        raise last  # type: ignore[misc]

    def _pods(self) -> List[dict]:
        resp = self.api.get(pods_path(self.namespace, self.cluster_name))
        return resp.get("items", [])

    # -- NodeProvider surface -------------------------------------------
    def create_node(self, node_type, resources, labels) -> ProviderNode:
        """Ask for one more replica of ``node_type``'s group.  One CR
        read + one merge patch; the operator does the rest."""
        def bump(groups: List[dict]) -> dict:
            for g in groups:
                if g.get("name") == node_type:
                    g["replicas"] = int(g.get("replicas", 0)) + 1
                    return g
            raise KeyError(
                f"RtCluster {self.cluster_name} has no worker group "
                f"{node_type!r} (groups: "
                f"{[g.get('name') for g in groups]})"
            )

        g = self._mutate_groups(bump)
        logger.info(
            "scaled group %s of %s to %s replicas",
            node_type, self.cluster_name, g["replicas"],
        )
        return ProviderNode(
            provider_id=f"pending-{node_type}-{g['replicas']}",
            node_type=node_type,
            meta={"pending": True},
        )

    def terminate_node(self, node: ProviderNode) -> None:
        """Name the pod in workersToDelete AND drop replicas by one in
        the same patch — the operator deletes exactly that pod instead
        of a random scale-down victim (the batching provider's
        scale_request shape)."""
        if node.meta.get("pending"):
            # never manifested: just lower the replica count
            pod_name = None
        else:
            pod_name = node.provider_id
        def drop(groups: List[dict]) -> Optional[dict]:
            for g in groups:
                if g.get("name") == node.node_type:
                    g["replicas"] = max(0, int(g.get("replicas", 0)) - 1)
                    if pod_name is not None:
                        wtd = list(g.get("workersToDelete") or [])
                        if pod_name not in wtd:
                            wtd.append(pod_name)
                        g["workersToDelete"] = wtd
                    return g
            return None  # group vanished: nothing to do

        g = self._mutate_groups(drop)
        if g is None:
            return
        logger.info(
            "descaled group %s of %s to %s replicas (deleting %s)",
            node.node_type, self.cluster_name, g["replicas"], pod_name,
        )

    def non_terminated_nodes(self) -> List[ProviderNode]:
        cr = self._get_cr()
        pods = self._pods()
        out: List[ProviderNode] = []
        per_group_live: Dict[str, int] = {}
        deleting = {
            name
            for g in self._groups(cr)
            for name in (g.get("workersToDelete") or [])
        }
        for pod in pods:
            meta = pod.get("metadata", {})
            name = meta.get("name", "")
            phase = (pod.get("status") or {}).get("phase", "")
            if phase in ("Succeeded", "Failed") or name in deleting:
                continue
            group = (meta.get("labels") or {}).get(f"{GROUP}/group", "")
            node_id = (meta.get("annotations") or {}).get(
                f"{GROUP}/node-id"
            )
            per_group_live[group] = per_group_live.get(group, 0) + 1
            out.append(
                ProviderNode(
                    provider_id=name,
                    node_type=group,
                    node_id_hex=node_id,
                    meta={"phase": phase},
                )
            )
        # replicas the operator has not manifested yet count as pending
        # supply, or every reconcile pass would launch another copy
        for g in self._groups(cr):
            want = int(g.get("replicas", 0))
            have = per_group_live.get(g.get("name", ""), 0)
            for i in range(max(0, want - have)):
                out.append(
                    ProviderNode(
                        provider_id=f"pending-{g.get('name')}-{i}",
                        node_type=g.get("name", ""),
                        meta={"pending": True},
                    )
                )
        return out
