"""Node providers: how the autoscaler actually creates/destroys nodes.

Role-equivalent of the reference's NodeProvider plugin surface (ray:
python/ray/autoscaler/node_provider.py:23) with the launch-config
machinery dropped: a provider maps (node_type -> running raylet) and the
autoscaler owns all policy.  `LocalSubprocessProvider` is the
FakeMultiNodeProvider analogue (ray: autoscaler/_private/fake_multi_node/
node_provider.py) — it spawns real raylet subprocesses on this host, so
autoscaling tests exercise the same node lifecycle as production.
"""

from __future__ import annotations

import logging
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class ProviderNode:
    provider_id: str
    node_type: str
    node_id_hex: Optional[str] = None  # raylet's cluster node id, once known
    proc: Optional[subprocess.Popen] = None
    meta: dict = field(default_factory=dict)


class NodeProvider:
    """Interface the autoscaler drives.  Implementations: local
    subprocesses (below), GKE/GCE TPU slices (deployment-specific)."""

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> ProviderNode:
        raise NotImplementedError

    def terminate_node(self, node: ProviderNode) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[ProviderNode]:
        raise NotImplementedError


class LocalSubprocessProvider(NodeProvider):
    """Raylet subprocesses on the local host (tests / single TPU-VM)."""

    def __init__(self, gcs_address: str, session_dir: str):
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self._nodes: Dict[str, ProviderNode] = {}
        self._counter = 0
        self._lock = threading.Lock()

    def create_node(self, node_type, resources, labels) -> ProviderNode:
        from ray_tpu.core import node as node_mod

        labels = dict(labels)
        labels["ray_tpu.node_type"] = node_type
        proc, address, node_id, _store = node_mod.start_raylet(
            self.gcs_address,
            self.session_dir,
            dict(resources),
            labels=labels,
        )
        with self._lock:
            self._counter += 1
            pn = ProviderNode(
                provider_id=f"local-{self._counter}",
                node_type=node_type,
                node_id_hex=node_id,
                proc=proc,
            )
            self._nodes[pn.provider_id] = pn
        logger.info("provider launched %s (%s) as node %s",
                    pn.provider_id, node_type, node_id)
        return pn

    def terminate_node(self, node: ProviderNode) -> None:
        with self._lock:
            self._nodes.pop(node.provider_id, None)
        if node.proc is not None and node.proc.poll() is None:
            node.proc.terminate()
            try:
                node.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                node.proc.kill()
        logger.info("provider terminated %s", node.provider_id)

    def non_terminated_nodes(self) -> List[ProviderNode]:
        with self._lock:
            out = []
            for pn in list(self._nodes.values()):
                if pn.proc is not None and pn.proc.poll() is not None:
                    del self._nodes[pn.provider_id]  # crashed out of band
                else:
                    out.append(pn)
            return out

    def shutdown(self):
        for pn in self.non_terminated_nodes():
            self.terminate_node(pn)
