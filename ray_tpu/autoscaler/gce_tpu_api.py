"""REST client for the GCE TPU VM control plane (tpu.googleapis.com v2).

Role-equivalent of ray: python/ray/autoscaler/_private/gcp/node.py
(GCPTPUNode) + node_provider.py's resource CRUD, reshaped for the
slice-granular TpuPodProvider: a slice is provisioned as a QUEUED
RESOURCE (the capacity-friendly path GCP recommends for pods), becomes
READY when its underlying node is ACTIVE, and exposes one network
endpoint per host.

Transport is plain urllib against ``base_url`` (default the public API;
tests point it at a local fixture server — no egress, byte-for-byte
request assertions).  Auth is a bearer token from, in order: an
injected ``token_fn``, the ``RT_GCP_TOKEN`` env var, or the GCE
metadata server (the on-VM deployment path).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Callable, List, Optional

from ray_tpu.autoscaler.tpu_provider import GceTpuApi, TpuSlice, slice_shape

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/service-accounts/default/token"
)


class GceApiError(RuntimeError):
    def __init__(self, status: int, method: str, path: str, body: str):
        self.status = status
        super().__init__(f"{method} {path} -> HTTP {status}: {body[:500]}")


def _metadata_token() -> str:
    req = urllib.request.Request(
        _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())["access_token"]


class RestGceTpuApi(GceTpuApi):
    """Thin, deterministic REST client.  Every call is one request; all
    polling/waiting lives in the provider, so fixtures can assert the
    exact wire traffic."""

    def __init__(
        self,
        project: str,
        zone: str,
        runtime_version: str = "tpu-ubuntu2204-base",
        base_url: str = "https://tpu.googleapis.com",
        token_fn: Optional[Callable[[], str]] = None,
        network: str = "default",
    ):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self.base_url = base_url.rstrip("/")
        self.network = network
        self._token_fn = token_fn

    # -- transport -------------------------------------------------------
    def _token(self) -> str:
        if self._token_fn is not None:
            return self._token_fn()
        env = os.environ.get("RT_GCP_TOKEN")
        if env:
            return env
        return _metadata_token()

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        url = self.base_url + path
        data = None
        headers = {"Authorization": f"Bearer {self._token()}"}
        if body is not None:
            data = json.dumps(body, sort_keys=True).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            url, data=data, headers=headers, method=method
        )
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                payload = r.read()
        except urllib.error.HTTPError as e:
            raise GceApiError(
                e.code, method, path, e.read().decode(errors="replace")
            ) from None
        return json.loads(payload) if payload else {}

    # -- paths -----------------------------------------------------------
    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _qr_path(self, name: str = "") -> str:
        base = f"/v2/{self._parent}/queuedResources"
        return f"{base}/{name}" if name else base

    def _node_path(self, name: str = "") -> str:
        base = f"/v2/{self._parent}/nodes"
        return f"{base}/{name}" if name else base

    # -- GceTpuApi surface ------------------------------------------------
    def create_slice(self, name: str, accelerator_type: str) -> TpuSlice:
        slice_shape(accelerator_type)  # validate before any wire traffic
        body = {
            "tpu": {
                "node_spec": [
                    {
                        "parent": self._parent,
                        "node_id": name,
                        "node": {
                            "accelerator_type": accelerator_type,
                            "runtime_version": self.runtime_version,
                            "network_config": {
                                "network": self.network,
                                "enable_external_ips": False,
                            },
                        },
                    }
                ]
            },
        }
        self._request(
            "POST", self._qr_path() + f"?queued_resource_id={name}", body
        )
        return TpuSlice(
            name=name, accelerator_type=accelerator_type, state="CREATING"
        )

    def get_slice(self, name: str) -> Optional[TpuSlice]:
        try:
            qr = self._request("GET", self._qr_path(name))
        except GceApiError as e:
            if e.status == 404:
                return None
            raise
        qr_state = (qr.get("state") or {}).get("state", "")
        if qr_state in ("FAILED", "SUSPENDED"):
            return TpuSlice(
                name=name,
                accelerator_type=self._qr_accel(qr),
                state="FAILED",
                meta={"queued_resource_state": qr_state},
            )
        if qr_state != "ACTIVE":
            # WAITING_FOR_RESOURCES / PROVISIONING / ACCEPTED / CREATING
            return TpuSlice(
                name=name,
                accelerator_type=self._qr_accel(qr),
                state="CREATING",
                meta={"queued_resource_state": qr_state},
            )
        node = self._request("GET", self._node_path(name))
        endpoints = [
            f"{ep.get('ipAddress', '')}:{ep.get('port', 8470)}"
            for ep in node.get("networkEndpoints", [])
        ]
        state = "READY" if node.get("state") == "READY" else "CREATING"
        return TpuSlice(
            name=name,
            accelerator_type=node.get(
                "acceleratorType", self._qr_accel(qr)
            ),
            state=state,
            endpoints=endpoints,
            meta={"node_state": node.get("state", "")},
        )

    @staticmethod
    def _qr_accel(qr: dict) -> str:
        specs = ((qr.get("tpu") or {}).get("nodeSpec")
                 or (qr.get("tpu") or {}).get("node_spec") or [])
        if specs:
            node = specs[0].get("node", {})
            return node.get("acceleratorType") or node.get(
                "accelerator_type", ""
            )
        return ""

    def delete_slice(self, name: str) -> None:
        # node first (ACTIVE queued resources refuse deletion while their
        # node lives), then the queued resource; 404s are success — the
        # caller wants it GONE, and retries must be idempotent
        for method, path in (
            ("DELETE", self._node_path(name)),
            ("DELETE", self._qr_path(name)),
        ):
            try:
                self._request(method, path)
            except GceApiError as e:
                if e.status != 404:
                    raise

    def list_slices(self) -> List[TpuSlice]:
        out: List[TpuSlice] = []
        page_token = ""
        while True:
            path = self._qr_path()
            if page_token:
                path += f"?page_token={page_token}"
            resp = self._request("GET", path)
            for qr in resp.get("queuedResources", []):
                name = qr.get("name", "").rsplit("/", 1)[-1]
                qr_state = (qr.get("state") or {}).get("state", "")
                state = {
                    "ACTIVE": "READY",
                    "FAILED": "FAILED",
                    "SUSPENDED": "FAILED",
                }.get(qr_state, "CREATING")
                out.append(
                    TpuSlice(
                        name=name,
                        accelerator_type=self._qr_accel(qr),
                        state=state,
                        meta={"queued_resource_state": qr_state},
                    )
                )
            page_token = resp.get("nextPageToken", "")
            if not page_token:
                return out
