"""Autoscaler: elastic nodes driven by pending demand.

Role-equivalent of the reference's autoscaler v2 (ray:
python/ray/autoscaler/v2/scheduler.py:624, instance_manager/
reconciler.py:53) collapsed to the TPU shape of the problem: node types
are slice shapes, demand is pending leases + unplaced placement-group
bundles read straight from the GCS, and the reconcile loop is a single
bin-packing pass — no instance-manager state machine, because TPU slice
provisioning is a single create/delete call per node.
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.node_provider import (
    LocalSubprocessProvider,
    NodeProvider,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "NodeTypeConfig",
    "NodeProvider",
    "LocalSubprocessProvider",
]
