"""Declarative cluster launcher: ``ray_tpu up/down cluster.yaml``.

Role-equivalent of ray: `ray up` / `ray down`
(python/ray/scripts/scripts.py:1279, autoscaler/_private/commands.py:221)
— reshaped for TPU: node types are slice shapes, and the head +
autoscaler monitor come up with one command.

YAML schema::

    cluster_name: demo
    provider:
      type: local | gce_tpu | kuberay
      # gce_tpu: project_id, zone, api_base_url?, cpus_per_host?
      # kuberay:  namespace, kuberay_cluster_name?, api_base_url?
    head:
      resources: {CPU: 4}
    available_node_types:
      v5e-8:                       # gce_tpu: must be an accelerator_type
        resources: {CPU: 8, TPU: 8}
        min_workers: 1
        max_workers: 4
    idle_timeout_s: 60
    autoscaler_interval_s: 1.0

``up`` starts the head (GCS + raylet), spawns the autoscaler monitor as
a daemon process driving the declared provider, and records the cluster
under ``/tmp/ray_tpu_clusters/<name>.json``.  ``down`` terminates every
provider node, the monitor, and the head, then deletes the record.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

import yaml

from ray_tpu.autoscaler.autoscaler import NodeTypeConfig

logger = logging.getLogger(__name__)

_STATE_DIR = "/tmp/ray_tpu_clusters"


class ClusterConfigError(ValueError):
    pass


def load_cluster_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        cfg = yaml.safe_load(f)
    if not isinstance(cfg, dict):
        raise ClusterConfigError(f"{path}: top level must be a mapping")
    for key in ("cluster_name", "provider", "available_node_types"):
        if key not in cfg:
            raise ClusterConfigError(f"{path}: missing required key {key!r}")
    ptype = (cfg["provider"] or {}).get("type")
    if ptype not in ("local", "gce_tpu", "kuberay"):
        raise ClusterConfigError(
            f"{path}: provider.type must be local|gce_tpu|kuberay, "
            f"got {ptype!r}"
        )
    if ptype == "gce_tpu":
        for k in ("project_id", "zone"):
            if k not in cfg["provider"]:
                raise ClusterConfigError(
                    f"{path}: provider.{k} is required for gce_tpu"
                )
    for name, nt in cfg["available_node_types"].items():
        if not isinstance(nt, dict) or "resources" not in nt:
            raise ClusterConfigError(
                f"{path}: node type {name!r} needs a resources mapping"
            )
        if int(nt.get("min_workers", 0)) > int(nt.get("max_workers", 100)):
            raise ClusterConfigError(
                f"{path}: node type {name!r} has min_workers > max_workers"
            )
    return cfg


def node_type_configs(cfg: Dict[str, Any]) -> List[NodeTypeConfig]:
    return [
        NodeTypeConfig(
            name,
            {k: float(v) for k, v in nt["resources"].items()},
            int(nt.get("min_workers", 0)),
            int(nt.get("max_workers", 100)),
            dict(nt.get("labels") or {}),
        )
        for name, nt in cfg["available_node_types"].items()
    ]


def build_provider(cfg: Dict[str, Any], gcs_address: str, session_dir: str):
    """Instantiate the NodeProvider the config declares.  Used by the
    monitor process (autoscaler.main --cluster-config) and by down()."""
    p = cfg["provider"]
    ptype = p["type"]
    if ptype == "local":
        from ray_tpu.autoscaler.node_provider import LocalSubprocessProvider

        return LocalSubprocessProvider(gcs_address, session_dir)
    if ptype == "gce_tpu":
        from ray_tpu.autoscaler.gce_tpu_api import RestGceTpuApi
        from ray_tpu.autoscaler.tpu_provider import TpuPodProvider

        api = RestGceTpuApi(
            project=p["project_id"],
            zone=p["zone"],
            base_url=p.get("api_base_url", "https://tpu.googleapis.com"),
            token_fn=(lambda: p["api_token"]) if p.get("api_token") else None,
            runtime_version=p.get(
                "runtime_version", "tpu-ubuntu2204-base"
            ),
        )
        return TpuPodProvider(
            gcs_address,
            session_dir,
            api=api,
            cpus_per_host=float(p.get("cpus_per_host", 4.0)),
            slice_ready_timeout_s=float(
                p.get("slice_ready_timeout_s", 1800.0)
            ),
            poll_interval_s=float(p.get("poll_interval_s", 5.0)),
        )
    if ptype == "kuberay":
        from ray_tpu.autoscaler.k8s_provider import (
            KubeRayProvider,
            RestKubeApi,
        )

        api = RestKubeApi(
            base_url=p.get("api_base_url"),
            token_fn=(lambda: p["api_token"]) if p.get("api_token") else None,
        )
        return KubeRayProvider(
            api,
            p.get("namespace", "default"),
            p.get("kuberay_cluster_name", cfg["cluster_name"]),
        )
    raise ClusterConfigError(f"unknown provider type {ptype!r}")


# ---- cluster state records -------------------------------------------------

def _state_path(cluster_name: str) -> str:
    return os.path.join(_STATE_DIR, f"{cluster_name}.json")


def _save_state(cluster_name: str, state: Dict[str, Any]) -> None:
    os.makedirs(_STATE_DIR, exist_ok=True)
    with open(_state_path(cluster_name), "w") as f:
        json.dump(state, f, indent=2)


def load_state(cluster_name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_state_path(cluster_name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


# ---- up / down -------------------------------------------------------------

def up(config_path: str, wait_min_workers_s: float = 0.0) -> Dict[str, Any]:
    """Provision the declared cluster: head + autoscaler monitor.

    Returns the cluster state record.  With ``wait_min_workers_s`` > 0,
    blocks until every node type reached min_workers (or the deadline).
    """
    from ray_tpu.core import node as node_mod

    cfg = load_cluster_config(config_path)
    name = cfg["cluster_name"]
    if load_state(name) is not None:
        raise ClusterConfigError(
            f"cluster {name!r} is already up (state file "
            f"{_state_path(name)}); run `ray_tpu down` first"
        )

    session_dir = node_mod.default_session_dir()
    gcs_proc, gcs_address = node_mod.start_gcs(session_dir)
    head_res = dict(
        (cfg.get("head") or {}).get("resources") or {"CPU": 4.0}
    )
    try:
        raylet_proc, _raylet_addr, head_node_id, _store = (
            node_mod.start_raylet(
                gcs_address, session_dir, head_res,
                labels={"ray_tpu.head": "1"},
            )
        )
    except BaseException:
        gcs_proc.terminate()
        raise

    # the monitor daemon rebuilds the provider from the SAME yaml —
    # one source of truth, survives launcher exit
    monitor = subprocess.Popen(
        [
            sys.executable, "-m", "ray_tpu.autoscaler.autoscaler",
            "--gcs", gcs_address,
            "--session-dir", session_dir,
            "--cluster-config", os.path.abspath(config_path),
            "--interval", str(cfg.get("autoscaler_interval_s", 1.0)),
            "--idle-timeout", str(cfg.get("idle_timeout_s", 60.0)),
        ],
        stdout=open(os.path.join(session_dir, "autoscaler.log"), "ab"),
        stderr=subprocess.STDOUT,
    )
    state = {
        "cluster_name": name,
        "config_path": os.path.abspath(config_path),
        "gcs_address": gcs_address,
        "session_dir": session_dir,
        "head_node_id": head_node_id,
        "gcs_pid": gcs_proc.pid,
        "raylet_pid": raylet_proc.pid,
        "monitor_pid": monitor.pid,
        "started_at": time.time(),
    }
    _save_state(name, state)
    if wait_min_workers_s > 0:
        _wait_min_workers(cfg, gcs_address, wait_min_workers_s)
    return state


def _wait_min_workers(cfg, gcs_address: str, timeout_s: float) -> None:
    """Poll the GCS until every node type's min_workers are alive."""
    want = {
        name: int(nt.get("min_workers", 0))
        for name, nt in cfg["available_node_types"].items()
        if int(nt.get("min_workers", 0)) > 0
    }
    if not want:
        return
    deadline = time.monotonic() + timeout_s
    counts: Dict[str, int] = {}
    while time.monotonic() < deadline:
        nodes = _query_nodes(gcs_address)
        # min_workers is PROVIDER-node granular: a TPU slice of N hosts
        # counts once (distinct ray_tpu.slice label), a plain node counts
        # itself — and a slice only counts when ALL its hosts are alive
        per_slice: Dict[str, Dict[str, int]] = {}
        counts = {}
        for n in nodes:
            if not n.get("alive"):
                continue
            labels = n.get("labels") or {}
            nt = labels.get("ray_tpu.node_type")
            if not nt:
                continue
            sl = labels.get("ray_tpu.slice")
            if sl is None:
                counts[nt] = counts.get(nt, 0) + 1
            else:
                per_slice.setdefault(nt, {})
                per_slice[nt][sl] = per_slice[nt].get(sl, 0) + 1
        from ray_tpu.autoscaler.tpu_provider import slice_shape

        for nt, slices in per_slice.items():
            try:
                hosts_needed = slice_shape(nt)[0]
            except ValueError:
                hosts_needed = 1
            counts[nt] = counts.get(nt, 0) + sum(
                1 for c in slices.values() if c >= hosts_needed
            )
        if all(counts.get(k, 0) >= v for k, v in want.items()):
            return
        time.sleep(0.5)
    raise TimeoutError(
        f"cluster did not reach min_workers within {timeout_s:.0f}s "
        f"(want {want}, have {counts})"
    )


def _query_nodes(gcs_address: str) -> List[dict]:
    import asyncio

    from ray_tpu.core import rpc

    async def q():
        conn = await rpc.connect(gcs_address, timeout=5.0)
        try:
            return await conn.call("get_nodes", {})
        finally:
            await conn.close()

    return asyncio.run(q())


def _notify_raylet(address: str, method: str) -> None:
    import asyncio

    from ray_tpu.core import rpc

    async def q():
        conn = await rpc.connect(address, timeout=5.0)
        try:
            await conn.call(method, {}, timeout=10.0)
        finally:
            await conn.close()

    asyncio.run(q())


def down(config_path: str) -> Dict[str, int]:
    """Tear the cluster down: every provider node, the monitor, the head.

    Idempotent: a missing state file only skips the pid kills; provider
    resources are still enumerated and deleted (the fixture/down test
    contract: nothing queued may survive)."""
    cfg = load_cluster_config(config_path)
    name = cfg["cluster_name"]
    state = load_state(name)
    stats = {"provider_nodes": 0, "processes": 0}

    # monitor FIRST — it would otherwise relaunch nodes as we delete them
    if state:
        for key in ("monitor_pid",):
            stats["processes"] += _kill(state.get(key))

    gcs_address = (state or {}).get("gcs_address", "")
    session_dir = (state or {}).get("session_dir", "/tmp/ray_tpu")

    # drain every registered raylet via RPC — works for nodes whose pids
    # live in the (now dead) monitor or on OTHER hosts entirely
    if gcs_address:
        head_id = (state or {}).get("head_node_id")
        try:
            for n in _query_nodes(gcs_address):
                if not n.get("alive") or n.get("node_id") == head_id:
                    continue
                try:
                    _notify_raylet(n["address"], "shutdown_node")
                    stats["provider_nodes"] += 1
                except Exception:
                    logger.debug("drain of %s failed", n.get("address"))
        except Exception:
            logger.debug("GCS at %s unreachable during down", gcs_address)

    provider = build_provider(cfg, gcs_address, session_dir)
    for node in provider.non_terminated_nodes():
        try:
            provider.terminate_node(node)
            stats["provider_nodes"] += 1
        except Exception:
            logger.exception("terminating %s failed", node.provider_id)
    # gce: delete ANY leftover queued resource of this cluster's types
    # (e.g. slices from a crashed monitor that never registered nodes)
    deleter = getattr(provider, "api", None)
    if deleter is not None and hasattr(deleter, "list_slices"):
        for tpu in deleter.list_slices():
            try:
                deleter.delete_slice(tpu.name)
                stats["provider_nodes"] += 1
            except Exception:
                logger.exception("deleting slice %s failed", tpu.name)

    if state:
        for key in ("raylet_pid", "gcs_pid"):
            stats["processes"] += _kill(state.get(key))
        try:
            os.unlink(_state_path(name))
        except FileNotFoundError:
            pass
    return stats


def _kill(pid: Optional[int]) -> int:
    if not pid:
        return 0
    try:
        os.kill(pid, signal.SIGTERM)
        return 1
    except ProcessLookupError:
        return 0
