"""Block layer: pyarrow Tables in the object store.

Role-equivalent of ray: python/ray/data/block.py (Block, BlockAccessor:219)
+ arrow_block.py.  A Dataset is a list of ObjectRefs to Arrow tables;
accessors convert between rows / numpy / pandas views.  Arrow buffers ride
the serializer's out-of-band path, so block transfer between workers is
copy-light through the shm store.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

Block = pa.Table


def from_rows(rows: List[Dict[str, Any]]) -> Block:
    if not rows:
        return pa.table({})
    # rows from tensor-column blocks carry per-row ndarrays (iter_rows);
    # stack those columns back into tensor columns — from_pylist cannot
    # convert multi-dim ndarray cells
    first = rows[0]
    tensor_cols = [
        k for k, v in first.items()
        if isinstance(v, np.ndarray) and v.ndim >= 1
    ]
    if not tensor_cols:
        return pa.Table.from_pylist(rows)
    plain = [
        {k: v for k, v in r.items() if k not in tensor_cols} for r in rows
    ]
    arrays = {
        k: np.stack([r[k] for r in rows]) for k in tensor_cols
    }
    tensor_tbl = from_numpy(arrays)
    if plain[0]:
        plain_tbl = pa.Table.from_pylist(plain)
        for i, name in enumerate(tensor_tbl.schema.names):
            plain_tbl = plain_tbl.append_column(
                tensor_tbl.schema.field(name), tensor_tbl.column(i)
            )
        return plain_tbl
    return tensor_tbl

def from_numpy(arrays: Dict[str, np.ndarray]) -> Block:
    import json

    cols = {}
    fields = []
    for k, v in arrays.items():
        v = np.asarray(v)
        if v.ndim <= 1:
            arr = pa.array(v)
            fields.append(pa.field(k, arr.type))
        else:
            # tensors: fixed-shape lists (ragged unsupported on TPU anyway),
            # with the per-row shape kept in field metadata so to_numpy can
            # restore ndim>2 tensors exactly.  Explicit width: reshape(-1)
            # cannot infer a dimension when the array has zero rows.
            import math

            width = math.prod(v.shape[1:])
            flat = v.reshape(len(v), width)
            arr = pa.FixedSizeListArray.from_arrays(
                pa.array(flat.reshape(-1)), flat.shape[1]
            )
            fields.append(
                pa.field(
                    k,
                    arr.type,
                    metadata={"rt_tensor_shape": json.dumps(list(v.shape[1:]))},
                )
            )
        cols[k] = arr
    return pa.table(cols, schema=pa.schema(fields))


def from_pandas(df) -> Block:
    return pa.Table.from_pandas(df, preserve_index=False)


class BlockAccessor:
    """Views over one Arrow block (ray: BlockAccessor analogue)."""

    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    def to_pylist(self) -> List[Dict[str, Any]]:
        return self.block.to_pylist()

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        # tensor columns come back as per-row ndarrays with their original
        # shape (reference: row access on tensor extension columns), not
        # nested pylists
        tensor_cols = {
            f.name
            for f in self.block.schema
            if f.metadata and b"rt_tensor_shape" in f.metadata
        }
        if not tensor_cols:
            for batch in self.block.to_batches():
                yield from batch.to_pylist()
            return
        arrays = self.to_numpy()
        n = self.block.num_rows
        names = self.block.schema.names
        for i in range(n):
            yield {name: arrays[name][i] for name in names}

    def to_pandas(self):
        return self.block.to_pandas()

    def to_numpy(self) -> Dict[str, np.ndarray]:
        import json

        out = {}
        for name in self.block.column_names:
            col = self.block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                flat = col.combine_chunks().flatten().to_numpy(
                    zero_copy_only=False
                )
                field = self.block.schema.field(name)
                meta = field.metadata or {}
                shape_json = meta.get(b"rt_tensor_shape")
                if shape_json is not None:
                    shape = tuple(json.loads(shape_json))
                    out[name] = flat.reshape((-1,) + shape)
                else:
                    out[name] = flat.reshape(-1, width)
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def select(self, columns: List[str]) -> Block:
        return self.block.select(columns)


def concat_blocks(blocks: List[Block]) -> Block:
    nonempty = [b for b in blocks if b.num_rows > 0]
    if not nonempty:
        # all-empty: keep the SCHEMA (downstream group_by/sort need the
        # columns even for zero rows — a schemaless table breaks them)
        for b in blocks:
            if b.num_columns:
                return b.slice(0, 0)
        return pa.table({})
    return pa.concat_tables(nonempty, promote_options="default")


def empty_like(block: Optional[Block]) -> Block:
    return block.slice(0, 0) if block is not None else pa.table({})
