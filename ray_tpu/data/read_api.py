"""Dataset creation: in-memory sources and file IO.

Role-equivalent of ray: python/ray/data/read_api.py + datasource/.
Reads are parallelized per file / per range-slice into remote tasks
producing Arrow blocks.
"""

from __future__ import annotations

import glob as glob_mod
import os
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.dataset import Dataset, ReadTask

DEFAULT_BLOCKS = 8


# -- in-memory sources -----------------------------------------------------


def _range_block(lo: int, hi: int):
    return block_mod.from_numpy({"id": np.arange(lo, hi, dtype=np.int64)})


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    import builtins

    nb = min(override_num_blocks or DEFAULT_BLOCKS, max(1, n))
    step = (n + nb - 1) // nb
    return Dataset(
        [
            ReadTask(_range_block, i * step, min((i + 1) * step, n))
            for i in builtins.range(nb)
            if i * step < n
        ]
    )


def from_items(
    items: List[Any], *, override_num_blocks: Optional[int] = None
) -> Dataset:
    import builtins

    rows = [
        it if isinstance(it, dict) else {"item": it} for it in items
    ]
    nb = min(override_num_blocks or DEFAULT_BLOCKS, max(1, len(rows)))
    step = (len(rows) + nb - 1) // nb
    refs = []
    for i in builtins.range(nb):
        chunk = rows[i * step : (i + 1) * step]
        if chunk:
            refs.append(ray_tpu.put(block_mod.from_rows(chunk)))
    return Dataset(refs)


def from_numpy(arrays: Dict[str, np.ndarray]) -> Dataset:
    return Dataset([ray_tpu.put(block_mod.from_numpy(arrays))])


def from_pandas(df) -> Dataset:
    return Dataset([ray_tpu.put(block_mod.from_pandas(df))])


def from_arrow(table) -> Dataset:
    return Dataset([ray_tpu.put(table)])


# -- file sources ----------------------------------------------------------


def _expand_paths(paths, suffix: str) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                sorted(glob_mod.glob(os.path.join(p, f"*{suffix}")))
            )
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths!r}")
    return out


def _read_parquet_file(path, columns=None):
    import pyarrow.parquet as pq

    return pq.read_table(path, columns=columns)


# marks readers that accept a `columns=` kwarg, enabling the
# projection-pushdown rule in Dataset.select_columns
_read_parquet_file.__rt_projectable__ = True


def _read_csv_file(path):
    import pyarrow.csv as pcsv

    return pcsv.read_csv(path)


def _read_jsonl_file(path):
    import pyarrow.json as pjson

    return pjson.read_json(path)


def _read_text_file(path):
    with open(path, "r") as f:
        lines = [ln.rstrip("\n") for ln in f]
    return block_mod.from_rows([{"text": ln} for ln in lines])


def _read_npy_file(path):
    return block_mod.from_numpy({"data": np.load(path)})


def _read_binary_file(path):
    with open(path, "rb") as f:
        data = f.read()
    return block_mod.from_rows([{"bytes": data, "path": path}])


def _file_dataset(paths, suffix: str, reader) -> Dataset:
    """One lazy ReadTask per file: the read happens on a worker when the
    streaming window pulls the block, not at dataset-construction time."""
    return Dataset(
        [ReadTask(reader, f) for f in _expand_paths(paths, suffix)]
    )


def read_parquet(paths, *, columns=None, **kwargs) -> Dataset:
    if columns is not None:
        import functools

        reader = functools.partial(_read_parquet_file, columns=list(columns))
        return _file_dataset(paths, ".parquet", reader)
    return _file_dataset(paths, ".parquet", _read_parquet_file)


def read_csv(paths, **kwargs) -> Dataset:
    return _file_dataset(paths, ".csv", _read_csv_file)


def read_json(paths, **kwargs) -> Dataset:
    """JSONL files (ray: read_json uses pyarrow.json line-delimited)."""
    return _file_dataset(paths, ".jsonl", _read_jsonl_file)


def read_text(paths, **kwargs) -> Dataset:
    return _file_dataset(paths, ".txt", _read_text_file)


def read_numpy(paths, **kwargs) -> Dataset:
    return _file_dataset(paths, ".npy", _read_npy_file)


def read_binary_files(paths, **kwargs) -> Dataset:
    return _file_dataset(paths, "", _read_binary_file)


def _make_image_reader(size, mode):
    def _read_image_file(path):
        from PIL import Image

        img = Image.open(path)
        if mode is not None:
            img = img.convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))  # PIL takes (W, H)
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return block_mod.from_numpy({"image": arr[None, ...]})

    return _read_image_file


def read_images(paths, *, size=None, mode=None, **kwargs) -> Dataset:
    """Image files → tensor-column blocks (ray: read_images,
    data/_internal/datasource/image_datasource.py).  `size=(H, W)`
    resizes (required for batching images of mixed sizes); `mode` is a
    PIL convert mode ("RGB", "L", ...)."""
    reader = _make_image_reader(size, mode)
    paths = _expand_paths(paths, "")
    imgs = [
        p for p in paths
        if p.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".gif",
                               ".webp"))
    ]
    if not imgs:
        raise FileNotFoundError(f"no image files in {paths!r}")
    return Dataset([ReadTask(reader, f) for f in imgs])


def read_sql(sql: str, connection_factory) -> Dataset:
    """A SQL query → Dataset (ray: read_sql,
    data/_internal/datasource/sql_datasource.py).  `connection_factory`
    is a zero-arg callable returning a DBAPI2 connection (sqlite3,
    psycopg2, ...) — it must be picklable since the query runs on a
    worker inside the streaming window.

    The query runs as ONE read task (one block); shard large tables by
    issuing several read_sql calls with disjoint predicates and
    `Dataset.union`, like the reference's sharded read_sql."""

    def _read_sql_task():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        return block_mod.from_rows(
            [dict(zip(cols, r)) for r in rows]
        )

    return Dataset([ReadTask(_read_sql_task)])


# -- writers (attached to Dataset) ----------------------------------------


def _write(ds: Dataset, path: str, fmt: str) -> List[str]:
    os.makedirs(path, exist_ok=True)

    @ray_tpu.remote
    def write_one(block, out_path):
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pq.write_table(block, out_path)
        elif fmt == "csv":
            import pyarrow.csv as pcsv

            pcsv.write_csv(block, out_path)
        else:  # jsonl
            with open(out_path, "w") as f:
                for row in block.to_pylist():
                    import json

                    f.write(json.dumps(row) + "\n")
        return out_path

    suffix = {"parquet": ".parquet", "csv": ".csv", "jsonl": ".jsonl"}[fmt]
    refs = [
        write_one.remote(ref, os.path.join(path, f"part-{i:05d}{suffix}"))
        for i, ref in enumerate(ds.iter_block_refs())
    ]
    return ray_tpu.get(refs, timeout=600)


def _install_writers():
    Dataset.write_parquet = lambda self, path: _write(self, path, "parquet")
    Dataset.write_csv = lambda self, path: _write(self, path, "csv")
    Dataset.write_json = lambda self, path: _write(self, path, "jsonl")


_install_writers()
