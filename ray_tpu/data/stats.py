"""Dataset execution statistics.

Role-equivalent of ray: ``Dataset.stats()``
(python/ray/data/dataset.py:4573) + the _StatsActor
(data/_internal/stats.py) — per-stage wall time / blocks / rows / bytes,
collected from the fused stage tasks wherever they ran, plus cluster
store spill counters.

Stage tasks report fire-and-forget to one named stats actor; records
are keyed by a per-execution run id, so concurrent datasets (or
drivers) never mix.  Collection is always on (like the reference) and
costs one extra fire-and-forget actor call per block task.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu

STATS_ACTOR_NAME = "_rt_data_stats"
_MAX_RUNS = 256          # oldest runs evicted beyond this
_MAX_RECORDS_PER_STAGE = 10_000


@ray_tpu.remote
class _StatsActor:
    """Cluster-wide sink for stage-task measurements."""

    def __init__(self):
        # run_id -> stage -> list[(wall_s, rows, bytes)]
        self._runs: Dict[str, Dict[str, List[Tuple[float, int, int]]]] = {}
        self._order: List[str] = []

    def record(self, run_id: str, stage: str, wall_s: float, rows: int,
               nbytes: int) -> None:
        run = self._runs.get(run_id)
        if run is None:
            run = self._runs[run_id] = {}
            self._order.append(run_id)
            while len(self._order) > _MAX_RUNS:
                self._runs.pop(self._order.pop(0), None)
        recs = run.setdefault(stage, [])
        if len(recs) < _MAX_RECORDS_PER_STAGE:
            recs.append((wall_s, rows, nbytes))

    def get(self, run_ids: List[str]) -> Dict[str, dict]:
        return {
            rid: {k: list(v) for k, v in self._runs.get(rid, {}).items()}
            for rid in run_ids
        }


_handle_cache: Any = None


def stats_handle():
    """The shared stats actor (created on first use, reused via name)."""
    global _handle_cache
    if _handle_cache is None:
        _handle_cache = _StatsActor.options(
            name=STATS_ACTOR_NAME, get_if_exists=True, num_cpus=0,
        ).remote()
    return _handle_cache


def record_stage(run_id: str, stage: str, t0: float, block) -> None:
    """Fire-and-forget one block-task measurement (called inside stage
    tasks on whatever worker ran them)."""
    try:
        rows = int(getattr(block, "num_rows", 0) or 0)
        nbytes = int(getattr(block, "nbytes", 0) or 0)
        # fire-and-forget BY DESIGN: stats are advisory, the enclosing
        # try swallows every failure, and holding refs would pin one
        # object per block task (rtflow RT202 audit: the ref is dropped,
        # never stored, so nothing pins the arena)
        # rtlint: disable-next=RT105
        stats_handle().record.remote(
            run_id, stage, time.perf_counter() - t0, rows, nbytes
        )
    except Exception:
        pass  # stats must never fail an execution


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"


def format_stats(
    runs: List[Tuple[str, str]],
    collected: Dict[str, dict],
    store_stats: Optional[dict] = None,
) -> str:
    """Render the reference-style per-stage summary.  ``runs`` is the
    execution lineage: (run_id, default_label) oldest first."""
    out: List[str] = []
    n = 0
    for run_id, label in runs:
        stages = collected.get(run_id) or {}
        if not stages:
            continue
        for stage, recs in stages.items():
            n += 1
            walls = [r[0] for r in recs]
            rows = sum(r[1] for r in recs)
            nbytes = sum(r[2] for r in recs)
            out.append(
                f"Stage {n} {stage or label}: {len(recs)} blocks executed"
            )
            out.append(
                "* Wall time: "
                f"{min(walls) * 1e3:.1f}ms min, {max(walls) * 1e3:.1f}ms "
                f"max, {sum(walls) / len(walls) * 1e3:.1f}ms mean, "
                f"{sum(walls):.3f}s total"
            )
            out.append(
                f"* Output rows: {rows} total, "
                f"{rows / max(1, len(recs)):.0f} mean per block"
            )
            out.append(
                f"* Output size: {_fmt_bytes(nbytes)} total, "
                f"{_fmt_bytes(nbytes / max(1, len(recs)))} mean per block"
            )
    if not out:
        out.append(
            "No execution stats recorded yet (consume or materialize the "
            "dataset first)."
        )
    if store_stats:
        spilled_n = sum(
            s.get("spill_count", 0) for s in store_stats.values()
            if isinstance(s, dict)
        )
        spilled_b = sum(
            s.get("spilled_bytes", 0) for s in store_stats.values()
            if isinstance(s, dict)
        )
        restored = sum(
            s.get("restore_count", 0) for s in store_stats.values()
            if isinstance(s, dict)
        )
        out.append(
            f"Cluster object store: {spilled_n} blocks spilled "
            f"({_fmt_bytes(spilled_b)}), {restored} restored"
        )
    return "\n".join(out)
