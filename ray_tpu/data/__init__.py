"""ray_tpu.data: distributed datasets over Arrow blocks.

Role-equivalent of ray: python/ray/data/.  Lazy transform plans with
fused per-block task execution; TPU ingest via iter_jax_batches.
"""

from ray_tpu.data.block import Block, BlockAccessor  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    ActorPoolStrategy,
    DataIterator,
    Dataset,
    GroupedData,
)
from ray_tpu.data.read_api import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
)

from ray_tpu.data.datasource import (  # noqa: F401
    Datasource,
    FileBasedDatasource,
    read_datasource,
)
