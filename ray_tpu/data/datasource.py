"""Datasource plugin API: bring-your-own formats for read/write.

Role-equivalent of ray: python/ray/data/datasource/datasource.py
(Datasource, Reader/ReadTask plugin surface) collapsed onto the lazy
ReadTask plan: a Datasource enumerates read tasks (one per block) and
optionally writes blocks back out.  Built-in file formats
(read_parquet & co.) are thin instances of FileBasedDatasource; custom
sources subclass Datasource:

    class MySource(ray_tpu.data.Datasource):
        def get_read_tasks(self, parallelism):
            return [ReadTask(self._load, shard) for shard in self.shards]

    ds = ray_tpu.data.read_datasource(MySource(...))
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

from ray_tpu.data.dataset import Dataset, ReadTask


class Datasource:
    """Subclass contract: get_read_tasks (required); write (optional)."""

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def write(self, blocks: Iterable[Any], path: str) -> List[str]:
        raise NotImplementedError(
            f"{type(self).__name__} does not support writing"
        )

    @property
    def name(self) -> str:
        return type(self).__name__


class FileBasedDatasource(Datasource):
    """One file per read task (the shape of every built-in format).

    ``reader(path) -> Block`` runs on a worker when the streaming window
    pulls the block.
    """

    def __init__(
        self,
        paths,
        *,
        suffix: str = "",
        reader: Optional[Callable[[str], Any]] = None,
    ):
        from ray_tpu.data.read_api import _expand_paths

        self._paths = _expand_paths(paths, suffix)
        if reader is not None:
            self._read_file = reader

    def _read_file(self, path: str):
        raise NotImplementedError("pass reader= or override _read_file")

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        return [ReadTask(self._read_file, p) for p in self._paths]


def read_datasource(
    datasource: Datasource, *, parallelism: int = -1
) -> Dataset:
    """Build a lazy Dataset from a datasource's read tasks (ray:
    ray.data.read_datasource)."""
    tasks = datasource.get_read_tasks(parallelism)
    if not tasks:
        raise ValueError(f"{datasource.name} produced no read tasks")
    return Dataset(tasks)
