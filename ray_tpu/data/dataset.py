"""Dataset: lazy transformation plan over distributed Arrow blocks.

Role-equivalent of ray: python/ray/data/dataset.py:137 (Dataset) with the
plan layer (data/_internal/logical/) collapsed to a fused-stage executor:
consecutive row/batch transforms fuse into ONE task per block (the
optimization the reference's rule optimizer does for map chains), with
shuffle ops (repartition / random_shuffle / sort / groupby) as stage
boundaries.  Blocks are ObjectRefs to pyarrow Tables, processed by
@remote tasks, so transform parallelism and locality come from the core
scheduler.

Execution is STREAMING by default (ray: data/_internal/execution/
streaming_executor.py:51 analogue): consumption iterates block-by-block
with a bounded in-flight window — sources are lazy ReadTasks executed
inside the fused stage task, at most `cfg.data_streaming_window` blocks
are being produced at once, and consumed blocks are freed by the core's
distributed refcounting as their refs drop — so a dataset much larger
than the object store flows through map→ingest at bounded memory
(backpressure = the consumer's pull rate).

The TPU-facing consumption path is iter_jax_batches(): dict-of-device
arrays, optionally laid out onto a mesh sharding for SPMD ingest, with
double-buffered jax.device_put so host→device transfer of batch N+1
overlaps the caller's step N compute.
"""

from __future__ import annotations

import builtins
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data import block as block_mod
from ray_tpu.data.block import Block, BlockAccessor, concat_blocks

BatchFormat = Union[str]  # "pyarrow" | "numpy" | "pandas"


# -- transform ops ---------------------------------------------------------


class ReadTask:
    """Lazy block source: fn(*args) → Block, run on a worker inside the
    fused stage task (ray: data ReadTask analogue).  Keeping sources lazy
    means a read is only issued when the streaming window pulls it."""

    def __init__(self, fn: Callable[..., "Block"], *args):
        self.fn = fn
        self.args = args

    def __call__(self) -> "Block":
        return self.fn(*self.args)


class _Op:
    def label(self) -> str:
        """Stage-name fragment for Dataset.stats()."""
        name = type(self).__name__.lstrip("_")
        fn = getattr(self, "fn", None)
        fn_name = getattr(fn, "__name__", None)
        return f"{name}({fn_name})" if fn_name else name


class ActorPoolStrategy:
    """Run a map_batches stage on a pool of stateful actors (ray:
    ray.data.ActorPoolStrategy; the actor_pool_map_operator role).
    The pool provisions between min_size and max_size actors, scaled to
    the stage's block count (no dynamic autoscaling mid-stage)."""

    def __init__(self, size: int = 2, min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self.min_size = min_size if min_size is not None else (
            size if max_size is None else 1
        )
        self.max_size = max_size if max_size is not None else size


class _MapBatches(_Op):
    def __init__(self, fn, batch_format="numpy", fn_kwargs=None):
        self.fn = fn
        self.batch_format = batch_format
        self.fn_kwargs = fn_kwargs or {}

    def apply(self, block: Block) -> Block:
        batch = _from_block(block, self.batch_format)
        out = self.fn(batch, **self.fn_kwargs)
        return _to_block(out)


class _MapRows(_Op):
    def __init__(self, fn):
        self.fn = fn

    def apply(self, block: Block) -> Block:
        rows = [self.fn(r) for r in BlockAccessor(block).iter_rows()]
        return block_mod.from_rows(rows)


class _FlatMap(_Op):
    def __init__(self, fn):
        self.fn = fn

    def apply(self, block: Block) -> Block:
        rows = []
        for r in BlockAccessor(block).iter_rows():
            rows.extend(self.fn(r))
        return block_mod.from_rows(rows)


class _Filter(_Op):
    def __init__(self, fn):
        self.fn = fn

    def apply(self, block: Block) -> Block:
        mask = [bool(self.fn(r)) for r in BlockAccessor(block).iter_rows()]
        return block.filter(pa.array(mask)) if len(mask) else block


def _from_block(block: Block, fmt: str):
    if fmt == "pyarrow":
        return block
    if fmt == "pandas":
        return BlockAccessor(block).to_pandas()
    return BlockAccessor(block).to_numpy()


def _to_block(batch) -> Block:
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        return block_mod.from_numpy(batch)
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return block_mod.from_pandas(batch)
    except ImportError:
        pass
    raise TypeError(
        f"map_batches fn must return dict/pyarrow.Table/DataFrame, got "
        f"{type(batch)}"
    )


def _kill_actor_pool(pool):
    import ray_tpu as _rt

    for a in pool:
        try:
            _rt.kill(a)
        except Exception:
            pass


def _apply_ops(block: Block, ops: List[_Op]) -> Block:
    for op in ops:
        block = op.apply(block)
    return block


# -- the dataset -----------------------------------------------------------


class Dataset:
    def __init__(self, block_refs: List[Any], ops: Optional[List[_Op]] = None,
                 exec_opts: Optional[dict] = None,
                 stats_lineage: Optional[tuple] = None):
        import uuid

        self._input_refs = block_refs
        self._ops: List[_Op] = ops or []
        self._materialized: Optional[List[Any]] = None  # refs post-ops
        # per-operator execution budget (ray: backpressure_policy/ +
        # per-op resource requests): {"num_cpus", "memory", "window"};
        # carried through map chains, reset at shuffle boundaries (each
        # operator configures its own stage)
        self._exec_opts: dict = dict(exec_opts or {})
        # execution-stats identity: this plan's stage tasks report under
        # _stats_run_id; _stats_lineage carries ancestor run ids across
        # shuffle/actor-pool boundaries so stats() shows the whole plan
        # (ray: Dataset.stats(), python/ray/data/dataset.py:4573)
        self._stats_run_id = uuid.uuid4().hex[:16]
        self._stats_lineage: tuple = stats_lineage or ()

    # -- plan building ---------------------------------------------------
    def _chain(self, op: _Op) -> "Dataset":
        return Dataset(self._input_refs, self._ops + [op], self._exec_opts,
                       self._stats_lineage)

    def with_resources(
        self,
        *,
        num_cpus: Optional[float] = None,
        memory: Optional[float] = None,
        window: Optional[int] = None,
    ) -> "Dataset":
        """Per-operator resource budget for this dataset's fused stage
        (reference role: per-op resource requests + the pluggable
        backpressure policies of data/_internal/execution/
        backpressure_policy/).  ``num_cpus``/``memory`` shape each stage
        task's scheduling demand; ``window`` caps this operator's
        in-flight block production independently of the global
        RT_DATA_STREAMING_WINDOW — a heavy stage (model inference) can
        be throttled to 2 blocks while light stages stream wide.
        Budgets carry through chained maps and reset at shuffle
        boundaries."""
        opts = dict(self._exec_opts)
        if num_cpus is not None:
            opts["num_cpus"] = num_cpus
        if memory is not None:
            opts["memory"] = memory
        if window is not None:
            if window < 1:
                raise ValueError("window must be >= 1")
            opts["window"] = window
        return Dataset(self._input_refs, list(self._ops), opts,
                       self._stats_lineage)

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_format: str = "numpy",
        fn_kwargs: Optional[dict] = None,
        compute: Any = None,
        concurrency: Optional[int] = None,
        fn_constructor_args: tuple = (),
        **_ignored,
    ) -> "Dataset":
        """Batch transform.  Plain functions fuse into per-block tasks
        (lazy).  A CLASS — or compute=ActorPoolStrategy(...) — runs on a
        pool of stateful actors instead (ray: actor_pool_map_operator
        role): the callable is constructed ONCE per actor (load a model
        there), blocks round-robin across the pool (each serial actor
        executes one block at a time), and the stage is an async plan
        boundary like the shuffles — the pool lives until the resulting
        Dataset is garbage-collected."""
        wants_actors = (
            isinstance(compute, ActorPoolStrategy)
            or compute == "actors"
            or isinstance(fn, type)
        )
        if wants_actors:
            if concurrency:
                lo = hi = concurrency
            elif isinstance(compute, ActorPoolStrategy):
                lo, hi = compute.min_size, compute.max_size
            else:
                lo = hi = 2
            return self._map_batches_actors(
                fn, lo, hi, batch_format, fn_kwargs or {},
                fn_constructor_args,
            )
        return self._chain(_MapBatches(fn, batch_format, fn_kwargs))

    def _map_batches_actors(
        self, fn, min_size: int, max_size: int, batch_format: str,
        fn_kwargs: dict, ctor_args: tuple,
    ) -> "Dataset":
        refs = self._execute()
        if not refs:
            return Dataset([])
        size = max(1, max(min_size, min(max_size, len(refs))))
        import uuid

        out_run_id = uuid.uuid4().hex[:16]
        stage_name = (
            f"MapBatches(actors:{getattr(fn, '__name__', type(fn).__name__)})"
        )

        @ray_tpu.remote
        class _MapWorker:
            def __init__(self, fn, ctor_args):
                self._callable = (
                    fn(*ctor_args) if isinstance(fn, type) else fn
                )

            def apply(self, block):
                import time as _time

                from ray_tpu.data import stats as stats_mod

                t0 = _time.perf_counter()
                batch = _from_block(block, batch_format)
                out = _to_block(self._callable(batch, **fn_kwargs))
                stats_mod.record_stage(out_run_id, stage_name, t0, out)
                return out

        pool = [
            _MapWorker.options(num_cpus=0.5).remote(fn, ctor_args)
            for _ in range(size)
        ]
        out = [pool[i % size].apply.remote(r) for i, r in enumerate(refs)]
        out_lineage = self._stats_lineage + ((self._stats_run_id, "Input"),)
        # The pool dies when the LAST output ref does — not with the
        # Dataset object, which a chained stage may drop while its refs
        # live on.  Finalizers hold the handles; consumption proceeds
        # asynchronously.  (Inline results ride replies; stored results
        # live in node shm independent of the producing actor, so actor
        # teardown after the refs die never strands data.)
        import weakref

        remaining = {"n": len(out)}

        def _one_ref_dead():
            remaining["n"] -= 1
            if remaining["n"] == 0:
                _kill_actor_pool(pool)

        for r in out:
            weakref.finalize(r, _one_ref_dead)
        ds = Dataset(out, stats_lineage=out_lineage)
        ds._stats_run_id = out_run_id
        return ds

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._chain(_MapRows(fn))

    def flat_map(self, fn: Callable[[dict], List[dict]]) -> "Dataset":
        return self._chain(_FlatMap(fn))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._chain(_Filter(fn))

    def select_columns(self, cols: List[str]) -> "Dataset":
        # logical-optimizer rule: projection pushdown (ray: data/_internal/
        # logical/rules — Project into Read).  A select directly over
        # column-capable read tasks (parquet) rewrites the readers to
        # fetch ONLY those columns instead of filtering post-read.
        if not self._ops and all(
            isinstance(s, ReadTask)
            and getattr(s.fn, "__rt_projectable__", False)
            for s in self._input_refs
        ):
            import functools

            pushed = [
                ReadTask(
                    functools.partial(s.fn, columns=list(cols)), *s.args
                )
                for s in self._input_refs
            ]
            return Dataset(pushed, exec_opts=self._exec_opts)
        return self.map_batches(
            lambda t: t.select(cols), batch_format="pyarrow"
        )

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map_batches(
            lambda t: t.drop_columns(cols), batch_format="pyarrow"
        )

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(t: pa.Table) -> pa.Table:
            return t.append_column(name, pa.array(fn(t)))

        return self.map_batches(add, batch_format="pyarrow")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def rename(t: pa.Table) -> pa.Table:
            return t.rename_columns(
                [mapping.get(c, c) for c in t.column_names]
            )

        return self.map_batches(rename, batch_format="pyarrow")

    # -- execution -------------------------------------------------------
    def _stage_label(self, src) -> str:
        head = "Read" if isinstance(src, ReadTask) else "Input"
        return "->".join([head] + [op.label() for op in self._ops])

    def _submit_stage(self, src) -> Any:
        """One fused read+transform task for one source → block ref."""
        ops = self._ops
        if not ops and not isinstance(src, ReadTask):
            return src  # already-materialized block, nothing to run
        run_id, stage = self._stats_run_id, self._stage_label(src)

        @ray_tpu.remote
        def run_stage(ops, src, run_id, stage):
            import time as _time

            from ray_tpu.data import stats as stats_mod

            t0 = _time.perf_counter()
            block = src() if isinstance(src, ReadTask) else src
            block = _apply_ops(block, ops)
            stats_mod.record_stage(run_id, stage, t0, block)
            return block

        kw = {
            k: self._exec_opts[k]
            for k in ("num_cpus", "memory")
            if self._exec_opts.get(k) is not None
        }
        if kw:
            run_stage = run_stage.options(**kw)
        return run_stage.remote(ops, src, run_id, stage)

    def iter_block_refs(self) -> Iterator[Any]:
        """Streaming execution: yield block refs in order with a bounded
        in-flight production window.  The consumer's pull rate is the
        backpressure (ray: streaming_executor_state.py:497 analogue,
        collapsed to a sliding window over the fused single-stage plan);
        dropping each yielded ref frees the block cluster-wide via the
        distributed refcounter."""
        if self._materialized is not None:
            yield from self._materialized
            return
        from collections import deque

        from ray_tpu.common.config import cfg

        window = max(
            1, self._exec_opts.get("window") or cfg.data_streaming_window
        )
        pending: Any = deque()
        srcs = iter(self._input_refs)
        for src in srcs:
            pending.append(self._submit_stage(src))
            if len(pending) >= window:
                break
        while pending:
            ref = pending.popleft()
            nxt = next(srcs, None)
            if nxt is not None:
                pending.append(self._submit_stage(nxt))
            yield ref

    def _execute(self) -> List[Any]:
        """Materialize the whole plan: every stage task in flight at once
        (used by shuffle boundaries and materialize(); streaming paths use
        iter_block_refs)."""
        if self._materialized is not None:
            return self._materialized
        self._materialized = [
            self._submit_stage(src) for src in self._input_refs
        ]
        return self._materialized

    def _blocks(self) -> List[Block]:
        return ray_tpu.get(self._execute(), timeout=600)

    def materialize(self) -> "Dataset":
        """Execute and pin the result (ray: Dataset.materialize)."""
        refs = self._execute()
        ray_tpu.wait(refs, num_returns=len(refs), timeout=600,
                     fetch_local=False)
        return Dataset(refs, stats_lineage=self._stats_lineage + (
            (self._stats_run_id, "Input"),
        ))

    def stats(self) -> str:
        """Per-stage execution statistics for everything this plan has
        RUN so far (ray: Dataset.stats, python/ray/data/dataset.py:4573):
        wall time min/max/mean/total, blocks, output rows and bytes per
        fused stage and shuffle map/reduce stage, plus cluster object
        store spill counters.  Stats are recorded as stage tasks execute;
        consume or materialize first for a complete picture."""
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.data import stats as stats_mod

        runs = list(self._stats_lineage) + [(self._stats_run_id, "Stage")]
        # stage tasks report fire-and-forget: poll until the record set
        # stabilizes (bounded) so a stats() right after consumption sees
        # the last stragglers
        import time as _time

        h = stats_mod.stats_handle()
        ids = [r[0] for r in runs]
        collected = ray_tpu.get(h.get.remote(ids), timeout=60)
        deadline = _time.monotonic() + 3.0
        while _time.monotonic() < deadline:
            _time.sleep(0.15)
            again = ray_tpu.get(h.get.remote(ids), timeout=60)
            if again == collected:
                break
            collected = again
        store_stats = None
        try:
            rt = get_runtime()
            store_stats = rt._run(rt.gcs.call("cluster_store_stats", {}))
        except Exception:
            pass
        return stats_mod.format_stats(runs, collected, store_stats)

    # -- shuffle-boundary ops -------------------------------------------
    # -- distributed shuffle core ---------------------------------------
    # Two-stage map/reduce exchange (ray: data/_internal/planner/exchange
    # push-based shuffle role): a map task splits every input block into
    # n_out partitions (num_returns=n_out), a reduce task per output
    # partition merges its pieces.  All block-sized work happens in
    # worker tasks — the driver never concatenates the dataset, so these
    # ops scale to datasets far beyond driver memory (blocks spill as
    # needed).

    def _block_counts(self, refs) -> List[int]:
        @ray_tpu.remote
        def _rows(b):
            return b.num_rows

        return ray_tpu.get([_rows.remote(r) for r in refs], timeout=600)

    @staticmethod
    def _exchange(refs, n_out: int, map_fn, reduce_fn,
                  map_args=None, stats_from: Optional["Dataset"] = None,
                  stage: str = "Shuffle") -> "Dataset":
        """map_fn(block, j_args...) -> tuple of n_out blocks;
        reduce_fn(*pieces) -> block.  map_args: per-input extra args."""
        if not refs:
            return Dataset([])
        import uuid

        out_run_id = uuid.uuid4().hex[:16]
        map_stage, reduce_stage = f"{stage}Map", f"{stage}Reduce"

        @ray_tpu.remote
        def shuffle_map(block, *args):
            import time as _time

            from ray_tpu.data import stats as stats_mod

            t0 = _time.perf_counter()
            pieces = tuple(map_fn(block, *args))
            stats_mod.record_stage(out_run_id, map_stage, t0, block)
            # num_returns=1 stores the RETURN VALUE as the single object:
            # unwrap, or the reduce would receive a 1-tuple
            return pieces if n_out > 1 else pieces[0]

        @ray_tpu.remote
        def shuffle_reduce(*parts):
            import time as _time

            from ray_tpu.data import stats as stats_mod

            t0 = _time.perf_counter()
            block = reduce_fn(list(parts))
            stats_mod.record_stage(out_run_id, reduce_stage, t0, block)
            return block

        map_outs = []
        for i, r in enumerate(refs):
            args = map_args[i] if map_args is not None else ()
            out = shuffle_map.options(num_returns=n_out).remote(r, *args)
            map_outs.append(out if n_out > 1 else [out])
        lineage = ()
        if stats_from is not None:
            lineage = stats_from._stats_lineage + (
                (stats_from._stats_run_id, "Input"),
            )
        ds = Dataset([
            shuffle_reduce.remote(*[mo[j] for mo in map_outs])
            for j in range(n_out)
        ], stats_lineage=lineage)
        ds._stats_run_id = out_run_id
        return ds

    def repartition(self, num_blocks: int) -> "Dataset":
        """Order-preserving rebalance into num_blocks equal-ish blocks."""
        refs = self._execute()
        if not refs:
            return Dataset([])
        counts = self._block_counts(refs)
        total = builtins.sum(counts)
        step = (total + num_blocks - 1) // num_blocks if total else 0
        offsets = np.concatenate([[0], np.cumsum(counts)])

        def cut(block, off):
            pieces = []
            for j in range(num_blocks):
                glo = min(j * step, total)
                ghi = min((j + 1) * step, total)
                lo = min(max(glo - off, 0), block.num_rows)
                hi = min(max(ghi - off, 0), block.num_rows)
                pieces.append(block.slice(lo, hi - lo))
            return pieces

        return self._exchange(
            refs, num_blocks, cut, concat_blocks,
            map_args=[(int(offsets[i]),) for i in range(len(refs))],
            stats_from=self, stage="Repartition",
        )

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Distributed uniform shuffle: rows scatter to random output
        partitions, each reduce locally permutes its merged rows."""
        refs = self._execute()
        if not refs:
            return Dataset([])
        n = len(refs)
        base = seed if seed is not None else int.from_bytes(
            os.urandom(4), "little"
        )

        def scatter(block, block_idx):
            rng = np.random.default_rng((base, 1, block_idx))
            shard = rng.integers(0, n, block.num_rows)
            return [
                block.take(pa.array(np.nonzero(shard == j)[0]))
                for j in range(n)
            ]

        def merge_permute(parts):
            whole = concat_blocks(parts)
            # deterministic per-partition permutation: partition identity
            # comes from the pieces' total, block_idx is unavailable — a
            # content-independent stream per reduce is enough for
            # uniformity given the random scatter
            rng = np.random.default_rng((base, 2, whole.num_rows))
            return whole.take(pa.array(rng.permutation(whole.num_rows)))

        return self._exchange(
            refs, n, scatter, merge_permute,
            map_args=[(i,) for i in range(n)],
            stats_from=self, stage="RandomShuffle",
        )

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed range-partitioned sort: sample keys → quantile
        boundaries → scatter by range → per-partition local sort.  The
        output blocks are globally ordered."""
        refs = self._execute()
        if not refs:
            return Dataset([])
        n = len(refs)
        order = "descending" if descending else "ascending"

        if n == 1:
            @ray_tpu.remote
            def sort_one(block):
                return block.sort_by([(key, order)])

            return Dataset([sort_one.remote(refs[0])])

        @ray_tpu.remote
        def sample_keys(block, cap=128):
            vals = block.column(key).to_numpy(zero_copy_only=False)
            if len(vals) > cap:
                idx = np.linspace(0, len(vals) - 1, cap).astype(np.int64)
                vals = vals[idx]
            return np.sort(vals)

        samples = np.concatenate(
            ray_tpu.get([sample_keys.remote(r) for r in refs], timeout=600)
        )
        samples = np.sort(samples)
        # n-1 quantile boundaries over the sampled key distribution
        bounds = samples[np.linspace(
            0, len(samples) - 1, n + 1
        ).astype(np.int64)][1:-1] if len(samples) else np.array([])

        def scatter(block):
            vals = block.column(key).to_numpy(zero_copy_only=False)
            part = np.searchsorted(bounds, vals, side="right")
            if descending:
                part = (n - 1) - part
            return [
                block.take(pa.array(np.nonzero(part == j)[0]))
                for j in range(n)
            ]

        def merge_sort(parts):
            return concat_blocks(parts).sort_by([(key, order)])

        return self._exchange(
            refs, n, scatter, merge_sort, stats_from=self, stage="Sort"
        )

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._execute())
        for o in others:
            refs.extend(o._execute())
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned column concatenation of two equal-length datasets
        (ray: python/ray/data/dataset.py:2215 Dataset.zip).  The right
        side's blocks are re-sliced to the left side's block boundaries,
        so each output block is produced by ONE task reading its left
        block plus the covering right-side ranges — no driver
        concatenation.  Colliding column names get a "_1" suffix, like
        the reference."""
        refs_a = self._execute()
        refs_b = other._execute()
        counts_a = self._block_counts(refs_a)
        counts_b = self._block_counts(refs_b)
        if builtins.sum(counts_a) != builtins.sum(counts_b):
            raise ValueError(
                f"zip requires equal row counts: "
                f"{builtins.sum(counts_a)} vs {builtins.sum(counts_b)}"
            )
        off_b = np.concatenate([[0], np.cumsum(counts_b)])

        @ray_tpu.remote
        def zip_blocks(a_block, spans, *b_blocks):
            pieces = [
                b.slice(start, stop - start)
                for b, (start, stop) in zip(b_blocks, spans)
            ]
            right = concat_blocks(pieces)
            out = a_block
            taken = set(a_block.column_names)
            for name, col in zip(right.column_names, right.columns):
                out_name = name if name not in taken else f"{name}_1"
                taken.add(out_name)
                out = out.append_column(out_name, col)
            return out

        out_refs = []
        row = 0
        for a_ref, n_rows in zip(refs_a, counts_a):
            lo, hi = row, row + n_rows
            spans, parts = [], []
            # right-side blocks overlapping [lo, hi)
            j0 = int(np.searchsorted(off_b, lo, side="right")) - 1
            j = max(0, j0)
            while j < len(refs_b) and off_b[j] < hi:
                s = max(lo, int(off_b[j])) - int(off_b[j])
                e = min(hi, int(off_b[j + 1])) - int(off_b[j])
                if e > s:
                    spans.append((s, e))
                    parts.append(refs_b[j])
                j += 1
            if not spans:
                # zero-row left block: a 0-row right slice keeps the
                # right SCHEMA in the output (a schemaless empty would
                # make sibling blocks inconsistent downstream)
                spans, parts = [(0, 0)], [refs_b[0]]
            out_refs.append(zip_blocks.remote(a_ref, spans, *parts))
            row = hi
        return Dataset(out_refs)

    def join(
        self,
        other: "Dataset",
        on: Union[str, List[str]],
        how: str = "inner",
        *,
        num_partitions: Optional[int] = None,
    ) -> "Dataset":
        """Distributed hash join (ray: Dataset.join).  Both sides
        hash-partition on the key (process-stable crc32, the groupby
        scatter), then each partition joins via pyarrow's native
        Table.join — n independent tasks, no driver concatenation."""
        join_type = {
            "inner": "inner",
            "left": "left outer",
            "right": "right outer",
            "outer": "full outer",
            "semi": "left semi",
            "anti": "left anti",
        }.get(how)
        if join_type is None:
            raise ValueError(
                f"unknown join how={how!r}; one of inner/left/right/"
                f"outer/semi/anti"
            )
        keys = [on] if isinstance(on, str) else list(on)
        refs_a = self._execute()
        refs_b = other._execute()
        if not refs_a:
            if join_type in (
                "inner", "left semi", "left anti", "left outer",
            ):
                return Dataset([])
            raise ValueError(
                f"{how} join with an empty left side is not supported "
                "(the output needs the left schema)"
            )
        if not refs_b:
            if join_type in ("inner", "left semi"):
                return Dataset([])
            if join_type == "left anti":
                return Dataset(list(refs_a))  # nothing to subtract
            raise ValueError(
                f"{how} join with an empty right side is not supported "
                "(the output needs the right schema)"
            )
        n = num_partitions or max(len(refs_a), len(refs_b), 1)
        key0 = keys[0]

        @ray_tpu.remote
        def scatter(block):
            pieces = GroupedData._hash_scatter(block, key0, n)
            return tuple(pieces) if n > 1 else pieces[0]

        @ray_tpu.remote
        def join_part(n_left, *parts):
            left = concat_blocks(list(parts[:n_left]))
            right = concat_blocks(list(parts[n_left:]))
            return left.join(right, keys=keys, join_type=join_type)

        def scatter_side(refs):
            outs = []
            for r in refs:
                o = scatter.options(num_returns=n).remote(r)
                outs.append(o if n > 1 else [o])
            return outs

        parts_a = scatter_side(refs_a)
        parts_b = scatter_side(refs_b)
        return Dataset([
            join_part.remote(
                len(parts_a),
                *[pa_[j] for pa_ in parts_a],
                *[pb_[j] for pb_ in parts_b],
            )
            for j in range(n)
        ])

    def limit(self, n: int) -> "Dataset":
        taken, out = 0, []
        for ref in self.iter_block_refs():
            if taken >= n:
                break
            b = ray_tpu.get(ref, timeout=600)
            keep = min(b.num_rows, n - taken)
            out.append(ray_tpu.put(b.slice(0, keep)))
            taken += keep
        return Dataset(out)

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Split into n datasets (per-worker ingest).

        equal=False stays LAZY: sources round-robin into the splits with
        the pending ops carried along, so each worker's shard streams
        independently.

        equal=True gives every shard EXACTLY total_rows // n rows
        (extras dropped) — the invariant SPMD train gangs need so all
        workers see the same batch count.  The plan executes into the
        OBJECT STORE (distributed, spill-backed) and shards carry lazy
        row-range slices over those blocks; nothing is concatenated in
        this process."""
        if equal:
            refs = self._execute()

            @ray_tpu.remote
            def _rows(b):
                return b.num_rows

            counts = ray_tpu.get(
                [_rows.remote(r) for r in refs], timeout=600
            )
            total = builtins.sum(counts)
            per = total // n

            def _slice_block(ref, lo, hi):
                return ray_tpu.get(ref, timeout=600).slice(lo, hi - lo)

            # walk blocks once, assigning contiguous [lo, hi) row ranges
            shards: List[List[Any]] = [[] for _ in range(n)]
            block_i, block_off = 0, 0
            for w in range(n):
                need = per
                while need > 0 and block_i < len(refs):
                    avail = counts[block_i] - block_off
                    take = min(avail, need)
                    if take > 0:
                        shards[w].append(ReadTask(
                            _slice_block, refs[block_i], block_off,
                            block_off + take,
                        ))
                    need -= take
                    block_off += take
                    if block_off >= counts[block_i]:
                        block_i += 1
                        block_off = 0
            return [Dataset(srcs) for srcs in shards]
        out: List[List[Any]] = [[] for _ in range(n)]
        for i, src in enumerate(self._input_refs):
            out[i % n].append(src)
        return [
            Dataset(srcs, ops=list(self._ops), exec_opts=self._exec_opts)
            for srcs in out
        ]

    def streaming_split(
        self, n: int, *, equal: bool = False, locality_hints=None
    ) -> List["DataIterator"]:
        """n per-worker streaming iterators (ray: Dataset.streaming_split,
        python/ray/data/dataset.py:1141) — the Train ingest surface.

        Each split streams its shard of source blocks through the pending
        lazy ops independently on the consuming worker, so ingest is
        worker-local with no central coordinator; `equal=True`
        materializes to balance rows exactly (needed when the consumers
        run in SPMD lockstep and must see the same batch count).
        locality_hints is accepted for API parity; block placement is
        store-driven here."""
        return [DataIterator(ds) for ds in self.split(n, equal=equal)]

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # -- consumption -----------------------------------------------------
    def count(self) -> int:
        @ray_tpu.remote
        def count_block(b):
            return b.num_rows

        # per-block counts consume each block promptly, so the stage
        # outputs free as fast as they are counted
        refs = [count_block.remote(r) for r in self.iter_block_refs()]
        return sum(ray_tpu.get(refs, timeout=600))

    def num_blocks(self) -> int:
        return len(self._input_refs)

    def schema(self):
        for ref in self.iter_block_refs():
            b = ray_tpu.get(ref, timeout=600)
            if b.num_rows or b.column_names:
                return b.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def take(self, n: int = 20) -> List[dict]:
        rows: List[dict] = []
        for ref in self.iter_block_refs():
            b = ray_tpu.get(ref, timeout=600)
            for r in BlockAccessor(b).iter_rows():
                rows.append(r)
                if len(rows) >= n:
                    return rows
        return rows

    def take_all(self) -> List[dict]:
        return [
            r
            for b in self._blocks()
            for r in BlockAccessor(b).iter_rows()
        ]

    def show(self, n: int = 20) -> None:
        for r in self.take(n):
            print(r)

    def iter_rows(self) -> Iterator[dict]:
        for ref in self.iter_block_refs():
            b = ray_tpu.get(ref, timeout=600)
            yield from BlockAccessor(b).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
    ) -> Iterator[Any]:
        """Stream batches, re-chunking across block boundaries; pulls one
        block at a time through the bounded streaming window."""
        carry: Optional[Block] = None
        for ref in self.iter_block_refs():
            b = ray_tpu.get(ref, timeout=600)
            if carry is not None and carry.num_rows:
                b = concat_blocks([carry, b])
                carry = None
            if batch_size is None:
                if b.num_rows:
                    yield _from_block(b, batch_format)
                continue
            off = 0
            while b.num_rows - off >= batch_size:
                yield _from_block(
                    b.slice(off, batch_size), batch_format
                )
                off += batch_size
            if off < b.num_rows:
                carry = b.slice(off)
        if carry is not None and carry.num_rows and not drop_last:
            yield _from_block(carry, batch_format)

    def iter_torch_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        dtypes: Optional[Dict[str, Any]] = None,
        device: Optional[str] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as torch tensors (ray: Dataset.iter_torch_batches).

        CPU-torch interop path (torch-TPU is not a thing here; jax owns
        the accelerator — use iter_jax_batches for device ingest)."""
        import torch

        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last,
        ):
            out = {}
            for k, v in batch.items():
                v = np.ascontiguousarray(v)
                if not v.flags.writeable:
                    # pyarrow's zero-copy to_numpy is read-only; torch
                    # mutation of such memory is undefined behavior
                    v = v.copy()
                t = torch.from_numpy(v)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device:
                    t = t.to(device)
                out[k] = t
            yield out

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        sharding=None,
        drop_last: bool = True,
        dtypes: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as device arrays, optionally placed onto a mesh sharding.

        The TPU ingest path: host Arrow blocks → numpy → jax.device_put
        (with a NamedSharding this feeds an SPMD step directly).  TPU
        wants static shapes, so drop_last defaults True.

        Double-buffered: batch N+1's device_put is issued (async) before
        batch N is yielded, so the host→device DMA overlaps the caller's
        step-N compute — ingest must not serialize against the train step
        (the prefetch the reference gets from iter_torch_batches'
        prefetch_batches).
        """
        import jax

        def to_device(batch):
            if dtypes:
                batch = {
                    k: v.astype(dtypes[k]) if k in dtypes else v
                    for k, v in batch.items()
                }
            if sharding is not None:
                return {
                    k: jax.device_put(v, sharding) for k, v in batch.items()
                }
            return {k: jax.device_put(v) for k, v in batch.items()}

        prev = None
        for batch in self.iter_batches(
            batch_size=batch_size, batch_format="numpy", drop_last=drop_last
        ):
            cur = to_device(batch)  # async transfer starts now
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev

    def to_pandas(self):
        return concat_blocks(self._blocks()).to_pandas()

    # -- stats / misc ----------------------------------------------------
    def sum(self, col: str):
        return self._agg(col, "sum")

    def min(self, col: str):
        return self._agg(col, "min")

    def max(self, col: str):
        return self._agg(col, "max")

    def mean(self, col: str):
        import pyarrow.compute as pc

        total, count = 0.0, 0
        for b in self._blocks():
            if b.num_rows:
                total += pc.sum(b.column(col)).as_py() or 0
                count += b.num_rows
        return total / count if count else None

    def _agg(self, col: str, kind: str):
        import pyarrow.compute as pc

        vals = []
        for b in self._blocks():
            if b.num_rows:
                vals.append(getattr(pc, kind)(b.column(col)).as_py())
        if not vals:
            return None
        return getattr(builtins, kind)(vals)

    def __repr__(self):
        lazy = sum(1 for s in self._input_refs if isinstance(s, ReadTask))
        return (
            f"Dataset(num_blocks={len(self._input_refs)}, "
            f"lazy_sources={lazy}, pending_ops={len(self._ops)})"
        )


class GroupedData:
    """Hash-partitioned groupby (ray: data/grouped_data.py analogue)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    @staticmethod
    def _hash_scatter(block, key: str, n: int):
        """Rows → n partitions by a process-stable hash of the group key
        (python hash() is salted per process, so crc32 instead)."""
        from zlib import crc32

        vals = block.column(key).to_pylist()
        part = np.fromiter(
            (crc32(repr(v).encode()) % n for v in vals),
            np.int64, count=len(vals),
        )
        return [
            block.take(pa.array(np.nonzero(part == j)[0]))
            for j in range(n)
        ]

    def _aggregate(self, aggs: Dict[str, str]) -> Dataset:
        """aggs: {column: 'sum'|'mean'|'min'|'max'|'count'}

        Distributed: hash-partition by the group key (every key lands
        whole in exactly one partition, so per-partition aggregates are
        exact), aggregate per partition, no driver concatenation."""
        key = self._key
        refs = self._ds._execute()
        if not refs:
            return Dataset([])
        n = len(refs)
        agg_list = [(c, k) for c, k in aggs.items()]

        def scatter(block):
            return GroupedData._hash_scatter(block, key, n)

        def merge_agg(parts):
            return concat_blocks(parts).group_by(key).aggregate(agg_list)

        return Dataset._exchange(
            refs, n, scatter, merge_agg, stats_from=self._ds,
            stage="GroupByAgg",
        )

    def sum(self, col: str) -> Dataset:
        return self._aggregate({col: "sum"})

    def mean(self, col: str) -> Dataset:
        return self._aggregate({col: "mean"})

    def min(self, col: str) -> Dataset:
        return self._aggregate({col: "min"})

    def max(self, col: str) -> Dataset:
        return self._aggregate({col: "max"})

    def count(self) -> Dataset:
        return self._aggregate({self._key: "count"})


class DataIterator:
    """Per-worker streaming view of a Dataset split.

    Role-equivalent of ray: python/ray/data/iterator.py (DataIterator,
    returned by Dataset.streaming_split / passed to Train workers via
    get_dataset_shard).  Serializable: ships the shard's source refs and
    pending lazy ops to the consuming worker, which streams blocks from
    the object store through the ops locally."""

    def __init__(self, dataset: Dataset):
        self._ds = dataset

    def iter_batches(self, **kwargs) -> Iterator[Dict[str, Any]]:
        return self._ds.iter_batches(**kwargs)

    def iter_jax_batches(self, **kwargs) -> Iterator[Dict[str, Any]]:
        """Device-resident batches with double-buffered transfer — the
        TPU train-loop ingest path (see Dataset.iter_jax_batches)."""
        return self._ds.iter_jax_batches(**kwargs)

    def iter_torch_batches(self, **kwargs) -> Iterator[Dict[str, Any]]:
        return self._ds.iter_torch_batches(**kwargs)

    def iter_rows(self):
        return self._ds.iter_rows()

    def materialize(self) -> Dataset:
        return self._ds.materialize()

    def count(self) -> int:
        return self._ds.count()

    def __repr__(self):
        return f"DataIterator({self._ds!r})"
