"""ray_tpu: a TPU-native distributed compute framework.

Tasks, actors, and shared-memory objects on an asyncio+C++ core runtime;
gang scheduling via placement groups; SPMD parallelism over JAX device
meshes with XLA/ICI collectives; and AI libraries (train/tune/data/serve/
rllib) layered on top.  Role-equivalent to the reference framework (ray)
but designed TPU-first — see SURVEY.md at the repo root.
"""

from ray_tpu._version import version as __version__  # noqa: F401

_API_EXPORTS = {}


def __getattr__(name):
    # Lazy core-API import: importing `ray_tpu` must stay cheap (and free of
    # jax) so control-plane processes can use the package without pulling in
    # the full runtime.
    if name in (
        "init",
        "shutdown",
        "is_initialized",
        "remote",
        "get",
        "put",
        "wait",
        "kill",
        "cancel",
        "get_actor",
        "get_runtime_context",
        "available_resources",
        "cluster_resources",
        "nodes",
        "method",
        "ObjectRef",
        "ObjectRefGenerator",
        "ActorHandle",
        "timeline",
    ):
        from ray_tpu.core import api

        return getattr(api, name)
    if name in (
        "RayTpuError",
        "TaskError",
        "WorkerCrashedError",
        "ActorError",
        "ActorDiedError",
        "ObjectLostError",
        "GetTimeoutError",
        "TaskCancelledError",
        "RuntimeEnvSetupError",
        "NodeDiedError",
        "FencedError",
    ):
        # error types at the package top level, like ray.exceptions'
        # re-exports (ray: python/ray/exceptions.py)
        from ray_tpu.core import errors

        return getattr(errors, name)
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
