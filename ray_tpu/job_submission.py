"""Job submission: run driver entrypoints on the cluster head.

Role-equivalent of ray: dashboard/modules/job/job_manager.py:529
(JobManager) + python/ray/dashboard/modules/job/sdk.py
(JobSubmissionClient) without the HTTP dashboard in between: the job
manager lives inside the GCS process (rpc_submit_job & co.), spawns the
entrypoint as a subprocess with RT_ADDRESS pointing back at the cluster,
applies the job-level runtime_env (env_vars; working_dir extracted from
the content-addressed KV package), and tracks status + captured logs
under the session dir.

    client = JobSubmissionClient("127.0.0.1:6379")
    job_id = client.submit_job(entrypoint="python my_driver.py",
                               runtime_env={"working_dir": "./app"})
    client.get_job_status(job_id)   # PENDING/RUNNING/SUCCEEDED/FAILED/...
    client.get_job_logs(job_id)
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core import rpc

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class JobSubmissionClient:
    """Synchronous client against the head's GCS (ray: sdk.py:88)."""

    def __init__(self, address: str):
        self.address = address

    def _call(self, method: str, payload: dict) -> Any:
        async def go():
            conn = await rpc.connect(self.address)
            try:
                return await conn.call(method, payload, timeout=60.0)
            finally:
                await conn.close()

        return asyncio.run(go())

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: Optional[dict] = None,
        metadata: Optional[Dict[str, str]] = None,
        submission_id: Optional[str] = None,
    ) -> str:
        desc = None
        if runtime_env:
            from ray_tpu.core import runtime_env as rtenv_mod

            desc = rtenv_mod.normalize(
                runtime_env,
                kv_put=lambda sha, v: self._call(
                    "put_blob", {"sha": sha, "data": v}
                ),
                scope=self.address,
            )
        reply = self._call(
            "submit_job",
            {
                "entrypoint": entrypoint,
                "runtime_env": desc,
                "metadata": metadata or {},
                "submission_id": submission_id,
            },
        )
        return reply["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        return self._call("get_job_info", {"submission_id": submission_id})[
            "status"
        ]

    def get_job_info(self, submission_id: str) -> dict:
        return self._call("get_job_info", {"submission_id": submission_id})

    def get_job_logs(self, submission_id: str) -> str:
        return self._call("get_job_logs", {"submission_id": submission_id})

    def stop_job(self, submission_id: str) -> bool:
        return self._call("stop_job", {"submission_id": submission_id})

    def list_jobs(self) -> List[dict]:
        return self._call("list_jobs", {})

    def wait_until_finished(
        self, submission_id: str, timeout: float = 300.0
    ) -> str:
        deadline = time.monotonic() + timeout
        status = self.get_job_status(submission_id)
        while True:
            if status in (SUCCEEDED, FAILED, STOPPED):
                return status
            if time.monotonic() >= deadline:
                break
            time.sleep(0.5)
            status = self.get_job_status(submission_id)
        raise TimeoutError(
            f"job {submission_id} still {status!r} after {timeout}s"
        )
