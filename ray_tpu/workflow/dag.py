"""Task DAG nodes for workflows (ray: python/ray/dag/function_node.py).

`fn.bind(*args)` produces a FunctionNode whose args may themselves be
FunctionNodes; `ray_tpu.workflow.run` walks the graph, executes every
node as a normal remote task in dependency waves, and checkpoints each
completed step.
"""

from __future__ import annotations

from typing import Dict, List, Tuple


class FunctionNode:
    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    @property
    def name(self) -> str:
        fn = self.remote_fn._fn
        return getattr(fn, "__name__", "step")

    def __repr__(self):
        return f"FunctionNode({self.name})"


def topo_sort(root: FunctionNode) -> List[FunctionNode]:
    """Deterministic topological order (parents before children)."""
    order: List[FunctionNode] = []
    state: Dict[int, int] = {}

    def visit(n):
        if not isinstance(n, FunctionNode):
            return
        s = state.get(id(n))
        if s == 1:
            return
        if s == 0:
            raise ValueError("cycle detected in workflow DAG")
        state[id(n)] = 0
        for a in n.args:
            visit(a)
        for a in n.kwargs.values():
            visit(a)
        state[id(n)] = 1
        order.append(n)

    visit(root)
    return order


def step_ids(root: FunctionNode) -> List[Tuple[str, FunctionNode]]:
    """Stable step ids: topo index + function name.  Re-running the same
    DAG shape yields the same ids, which is what makes resume skip
    completed steps."""
    return [
        (f"{i:04d}_{n.name}", n) for i, n in enumerate(topo_sort(root))
    ]
