"""Durable workflow storage: one directory per workflow id.

Role-equivalent of ray: python/ray/workflow/workflow_storage.py — step
results and the pickled DAG live as files; writes are atomic
(tmp + rename) so a crash mid-checkpoint never leaves a half step that
resume would trust.

Layout::

    <root>/<workflow_id>/
        dag.pkl            cloudpickled FunctionNode graph
        meta.json          {status, created_at, finished_at, error}
        steps/<step_id>.pkl   checkpointed step outputs
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, List, Optional

import cloudpickle

from ray_tpu.common.config import cfg

RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
CANCELED = "CANCELED"


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class WorkflowStorage:
    def __init__(self, workflow_id: str, root: Optional[str] = None):
        self.workflow_id = workflow_id
        self.root = root or cfg.workflow_storage
        self.dir = os.path.join(self.root, workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        # NOTE: directories are created lazily by the write paths —
        # read-only queries of unknown ids must not pollute the root.

    # -- dag -----------------------------------------------------------

    def save_dag(self, node) -> None:
        os.makedirs(self.dir, exist_ok=True)
        _atomic_write(
            os.path.join(self.dir, "dag.pkl"), cloudpickle.dumps(node)
        )

    def load_dag(self):
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return pickle.loads(f.read())

    # -- meta ----------------------------------------------------------

    def save_meta(self, **updates) -> dict:
        meta = self.load_meta() or {
            "workflow_id": self.workflow_id,
            "created_at": time.time(),
        }
        meta.update(updates)
        os.makedirs(self.dir, exist_ok=True)
        _atomic_write(
            os.path.join(self.dir, "meta.json"),
            json.dumps(meta).encode(),
        )
        return meta

    def load_meta(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    # -- steps ---------------------------------------------------------

    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.steps_dir, step_id + ".pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_path(step_id))

    def save_step(self, step_id: str, value: Any) -> None:
        os.makedirs(self.steps_dir, exist_ok=True)
        _atomic_write(self._step_path(step_id), cloudpickle.dumps(value))

    def load_step(self, step_id: str) -> Any:
        with open(self._step_path(step_id), "rb") as f:
            return pickle.loads(f.read())

    def completed_steps(self) -> List[str]:
        try:
            return sorted(
                f[:-4]
                for f in os.listdir(self.steps_dir)
                if f.endswith(".pkl")
            )
        except FileNotFoundError:
            return []

    def delete(self) -> None:
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)


def list_workflow_ids(root: Optional[str] = None) -> List[str]:
    root = root or cfg.workflow_storage
    try:
        return sorted(
            d
            for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
    except FileNotFoundError:
        return []
