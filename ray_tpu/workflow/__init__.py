"""Durable workflows (ray: python/ray/workflow/)."""

from ray_tpu.workflow.api import (  # noqa: F401
    WorkflowError,
    WorkflowNotFoundError,
    delete,
    get_output,
    get_status,
    list_all,
    resume,
    run,
    run_async,
)
from ray_tpu.workflow.storage import (  # noqa: F401
    CANCELED,
    FAILED,
    RUNNING,
    SUCCEEDED,
)

__all__ = [
    "run",
    "run_async",
    "resume",
    "get_output",
    "get_status",
    "list_all",
    "delete",
    "WorkflowError",
    "WorkflowNotFoundError",
    "RUNNING",
    "SUCCEEDED",
    "FAILED",
    "CANCELED",
]
