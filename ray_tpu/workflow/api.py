"""Durable, resumable task graphs (ray: python/ray/workflow/api.py).

`run(dag)` executes a `fn.bind(...)` graph as normal remote tasks in
dependency waves; every completed step's output is checkpointed to
`WorkflowStorage` before its children launch, so a crash at any point
resumes from the last completed frontier with `resume(workflow_id)`.

Deliberate simplifications vs the reference (documented descopes):
- Static DAGs only — no in-step continuations (`workflow.continuation`)
  and no virtual actors (deprecated upstream).
- Checkpointing is per-step and driver-side; the reference's
  storage-backed ObjectRef dedup is subsumed by this repo's distributed
  refcounting for in-flight values.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.workflow import storage as _st
from ray_tpu.workflow.dag import FunctionNode, step_ids


class WorkflowError(RuntimeError):
    pass


class WorkflowNotFoundError(WorkflowError):
    pass


def _execute(store: _st.WorkflowStorage, root: FunctionNode) -> Any:
    """Run the DAG in dependency waves, skipping checkpointed steps."""
    import ray_tpu

    steps = step_ids(root)
    sid_of = {id(n): sid for sid, n in steps}
    done: Dict[str, Any] = {}
    for sid, _ in steps:
        if store.has_step(sid):
            done[sid] = store.load_step(sid)

    pending = {sid: n for sid, n in steps if sid not in done}
    inflight: Dict[Any, str] = {}  # ref -> step_id

    def ready(n: FunctionNode) -> bool:
        deps = [
            a for a in list(n.args) + list(n.kwargs.values())
            if isinstance(a, FunctionNode)
        ]
        return all(sid_of[id(d)] in done for d in deps)

    def resolve(v):
        return done[sid_of[id(v)]] if isinstance(v, FunctionNode) else v

    while pending or inflight:
        launched = []
        for sid, n in pending.items():
            if ready(n):
                args = [resolve(a) for a in n.args]
                kwargs = {k: resolve(v) for k, v in n.kwargs.items()}
                ref = n.remote_fn.remote(*args, **kwargs)
                inflight[ref] = sid
                launched.append(sid)
        for sid in launched:
            del pending[sid]
        if not inflight:
            raise WorkflowError(
                "workflow deadlocked: no step ready and none in flight"
            )
        ready_refs, _ = ray_tpu.wait(
            list(inflight), num_returns=1, timeout=None
        )
        for ref in ready_refs:
            sid = inflight.pop(ref)
            value = ray_tpu.get(ref)
            store.save_step(sid, value)  # checkpoint before children launch
            done[sid] = value

    return done[sid_of[id(root)]]


def run(
    dag: FunctionNode,
    *,
    workflow_id: Optional[str] = None,
    storage: Optional[str] = None,
) -> Any:
    """Execute a DAG durably; returns the root node's output."""
    if not isinstance(dag, FunctionNode):
        raise TypeError("workflow.run takes a FunctionNode from fn.bind(...)")
    wid = workflow_id or f"wf-{uuid.uuid4().hex[:12]}"
    store = _st.WorkflowStorage(wid, storage)
    if store.load_meta() is not None:
        # a fresh run must never inherit another DAG's step checkpoints
        # (step ids are topo-index+name and would collide silently);
        # the reference raises on duplicate ids the same way.
        raise WorkflowError(
            f"workflow id {wid!r} already exists; use workflow.resume() "
            "to continue it or workflow.delete() first"
        )
    store.save_dag(dag)
    store.save_meta(status=_st.RUNNING, error=None)
    try:
        out = _execute(store, dag)
    except Exception as e:  # noqa: BLE001 - recorded then re-raised
        store.save_meta(status=_st.FAILED, error=repr(e),
                        finished_at=time.time())
        raise
    store.save_meta(status=_st.SUCCEEDED, finished_at=time.time())
    return out


def run_async(dag: FunctionNode, *, workflow_id: Optional[str] = None,
              storage: Optional[str] = None):
    """Run in a background thread; returns a concurrent.futures.Future."""
    import concurrent.futures

    wid = workflow_id or f"wf-{uuid.uuid4().hex[:12]}"
    fut: concurrent.futures.Future = concurrent.futures.Future()

    def go():
        try:
            fut.set_result(run(dag, workflow_id=wid, storage=storage))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    t = threading.Thread(target=go, name=f"workflow-{wid}", daemon=True)
    t.start()
    fut.workflow_id = wid  # type: ignore[attr-defined]
    return fut


def resume(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Re-run a FAILED/RUNNING-at-crash workflow from its checkpoints."""
    store = _st.WorkflowStorage(workflow_id, storage)
    meta = store.load_meta()
    if meta is None:
        raise WorkflowNotFoundError(workflow_id)
    dag = store.load_dag()
    store.save_meta(status=_st.RUNNING, error=None)
    try:
        out = _execute(store, dag)
    except Exception as e:  # noqa: BLE001
        store.save_meta(status=_st.FAILED, error=repr(e),
                        finished_at=time.time())
        raise
    store.save_meta(status=_st.SUCCEEDED, finished_at=time.time())
    return out


def get_status(workflow_id: str, *, storage: Optional[str] = None) -> str:
    meta = _st.WorkflowStorage(workflow_id, storage).load_meta()
    if meta is None:
        raise WorkflowNotFoundError(workflow_id)
    return meta["status"]


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Output of a SUCCEEDED workflow (its root step's checkpoint)."""
    store = _st.WorkflowStorage(workflow_id, storage)
    meta = store.load_meta()
    if meta is None:
        raise WorkflowNotFoundError(workflow_id)
    if meta["status"] != _st.SUCCEEDED:
        raise WorkflowError(
            f"workflow {workflow_id} is {meta['status']}, not SUCCEEDED"
        )
    dag = store.load_dag()
    root_sid = step_ids(dag)[-1][0]
    return store.load_step(root_sid)


def list_all(*, storage: Optional[str] = None) -> List[dict]:
    out = []
    for wid in _st.list_workflow_ids(storage):
        meta = _st.WorkflowStorage(wid, storage).load_meta()
        if meta:
            out.append(meta)
    return out


def delete(workflow_id: str, *, storage: Optional[str] = None) -> None:
    _st.WorkflowStorage(workflow_id, storage).delete()
