"""TPU detection and resource modelling.

Role-equivalent of ray: python/ray/_private/accelerators/tpu.py:75-398 —
chip detection (:110-120), TPU_VISIBLE_CHIPS partitioning (:174-196), pod
topology resources and the "<pod>-head" coordinator resource (:376-397) —
redesigned for this framework: detection feeds the raylet's node resources,
chip assignment happens at lease time in the raylet (raylet.py), and slice
gang scheduling uses the slice-name resource + STRICT_PACK placement groups.
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys
from typing import Dict, Optional

from ray_tpu.common.config import cfg

TPU_RESOURCE = "TPU"


class TPUAcceleratorManager:
    """Detects local TPU chips and derives the node's TPU resources."""

    def __init__(self):
        self._num_chips: Optional[int] = None
        self._generation: Optional[str] = None

    def num_chips(self) -> int:
        if self._num_chips is None:
            self._num_chips = self._detect()
        return self._num_chips

    def _detect(self) -> int:
        if cfg.tpu_chips_override >= 0:
            return cfg.tpu_chips_override
        # 1) device files (real TPU VM: /dev/accel* or /dev/vfio/*)
        n = len(glob.glob("/dev/accel*"))
        if n == 0:
            vfio = [p for p in glob.glob("/dev/vfio/*") if p != "/dev/vfio/vfio"]
            n = len(vfio)
        if n > 0:
            return n
        # 2) ask jax in a subprocess (covers tunnelled/experimental platforms;
        #    a subprocess so this control process never claims the chips)
        try:
            out = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; ds=[d for d in jax.devices() if d.platform"
                    " not in ('cpu',)]; print(len(ds)); "
                    "print(ds[0].device_kind if ds else '')",
                ],
                env={
                    k: v
                    for k, v in os.environ.items()
                    if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
                },
                capture_output=True,
                timeout=60,
                text=True,
            )
            if out.returncode == 0:
                lines = out.stdout.strip().splitlines()
                if lines and lines[0].isdigit():
                    if len(lines) > 1 and lines[1]:
                        self._generation = _kind_to_generation(lines[1])
                    return int(lines[0])
        except Exception:
            pass
        return 0

    def generation(self) -> Optional[str]:
        if self._generation is None:
            env = os.environ.get("TPU_ACCELERATOR_TYPE", "")  # e.g. v5litepod-8
            if env:
                self._generation = env.split("-")[0]
        return self._generation

    def extra_resources(self) -> Dict[str, float]:
        """Generation/topology resources advertised alongside `TPU`.

        Mirrors the reference's auto custom resources (tpu.py:376-397):
          TPU-<gen>          — generation-tagged capacity
          <slice_name>       — 1.0 on every host of a named slice
          TPU-<slice>-head   — 1.0 on worker 0 only (coordinator election)
        """
        out: Dict[str, float] = {}
        gen = self.generation()
        n = self.num_chips()
        if gen and n:
            out[f"TPU-{gen}"] = float(n)
        slice_name = os.environ.get("TPU_NAME") or cfg.tpu_topology_override
        if slice_name and n:
            out[slice_name] = 1.0
            if _tpu_worker_id() == 0:
                out[f"TPU-{slice_name}-head"] = 1.0
        return out


def _tpu_worker_id() -> int:
    for var in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"):
        v = os.environ.get(var)
        if v is not None and v.isdigit():
            return int(v)
    return 0


def _kind_to_generation(device_kind: str) -> str:
    # e.g. "TPU v5 lite" -> "v5e", "TPU v4" -> "v4"
    k = device_kind.lower()
    if "v5" in k and "lite" in k:
        return "v5e"
    for tag in ("v6e", "v5p", "v5", "v4", "v3", "v2"):
        if tag in k:
            return tag
    return device_kind.replace(" ", "-")
