"""Multi-node cluster harness: many raylets on one machine.

Role-equivalent of ray: python/ray/cluster_utils.py:135 (Cluster,
add_node:201) — the workhorse of the reference's scheduler/failover tests.
Each add_node() starts a real raylet subprocess with its own shm store and
resource set, all registered to one GCS, so multi-node scheduling, object
transfer, placement groups, and node-death paths run for real on a single
host (e.g. CPU-only CI, or one TPU-VM).
"""

from __future__ import annotations

import subprocess
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu.core import node as node_mod


@dataclass
class ClusterNode:
    node_id: str
    address: str
    store_path: str
    proc: subprocess.Popen
    resources: Dict[str, float]

    def kill(self, graceful: bool = True):
        if self.proc.poll() is None:
            if graceful:
                self.proc.terminate()
            else:
                self.proc.kill()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=5)


class Cluster:
    def __init__(
        self,
        initialize_head: bool = False,
        connect: bool = False,
        head_node_args: Optional[dict] = None,
    ):
        self.session_dir = node_mod.default_session_dir()
        self.gcs_proc, self.address = node_mod.start_gcs(self.session_dir)
        self._nodes: List[ClusterNode] = []
        self.head_node: Optional[ClusterNode] = None
        self._connected = False
        if initialize_head:
            self.head_node = self.add_node(**(head_node_args or {}))
            if connect:
                self.connect()

    @property
    def gcs_address(self) -> str:
        return self.address

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_bytes: int = 0,
    ) -> ClusterNode:
        res = dict(resources or {})
        res["CPU"] = float(num_cpus)
        if num_tpus:
            res["TPU"] = float(num_tpus)
        proc, address, node_id, store_path = node_mod.start_raylet(
            self.address,
            self.session_dir,
            res,
            labels=labels,
            store_capacity=object_store_bytes,
        )
        node = ClusterNode(
            node_id=node_id,
            address=address,
            store_path=store_path,
            proc=proc,
            resources=res,
        )
        self._nodes.append(node)
        if self.head_node is None:
            self.head_node = node
        return node

    def remove_node(self, node: ClusterNode, allow_graceful: bool = True):
        """Kill a raylet (and its workers); the GCS sees a node death."""
        node.kill(graceful=allow_graceful)
        if node in self._nodes:
            self._nodes.remove(node)
        if self.head_node is node:
            self.head_node = self._nodes[0] if self._nodes else None

    def wait_for_nodes(self, timeout: float = 30.0) -> None:
        """Block until every added node is alive in the GCS view."""
        import ray_tpu

        deadline = time.monotonic() + timeout
        want = {n.node_id for n in self._nodes}
        alive: set = set()
        while time.monotonic() < deadline:
            if self._connected:
                alive = {
                    n["node_id"] for n in ray_tpu.nodes() if n["alive"]
                }
            else:
                alive = set(self._query_alive())
            if want <= alive:
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"nodes never all registered: want {want}, alive {alive}"
        )

    def _query_alive(self) -> List[str]:
        import asyncio

        from ray_tpu.core import rpc

        async def go():
            conn = await rpc.connect(self.address)
            try:
                nodes = await conn.call("get_nodes", {})
            finally:
                await conn.close()
            return [n["node_id"] for n in nodes if n["alive"]]

        return asyncio.run(go())

    def connect(self):
        """Attach this process as a driver to the cluster."""
        import ray_tpu

        ray_tpu.init(address=self.address)
        self._connected = True

    def kill_gcs(self):
        """kill -9 the GCS process (head fault injection)."""
        if self.gcs_proc.poll() is None:
            self.gcs_proc.kill()
            self.gcs_proc.wait(timeout=5)

    def restart_gcs(self, timeout: float = 30.0):
        """Restart the GCS on the SAME port with the same session dir, so
        raylets/drivers holding ReconnectingConnections re-attach and the
        checkpoint restores cluster state (ray: GCS FT with external Redis;
        here the CheckpointStore under the session dir)."""
        self.kill_gcs()
        host, port_s = self.address.rsplit(":", 1)
        deadline = time.monotonic() + timeout
        last_exc: Optional[BaseException] = None
        while time.monotonic() < deadline:
            try:
                self.gcs_proc, addr = node_mod.start_gcs(
                    self.session_dir, host=host, port=int(port_s)
                )
                assert addr == self.address, (addr, self.address)
                return
            except Exception as e:  # port may linger in TIME_WAIT briefly
                last_exc = e
                time.sleep(0.3)
        raise RuntimeError(f"GCS restart failed: {last_exc!r}")

    def shutdown(self):
        """Tear down all raylets and the GCS."""
        for node in list(self._nodes):
            node.kill(graceful=True)
        self._nodes.clear()
        self.head_node = None
        if self.gcs_proc.poll() is None:
            self.gcs_proc.terminate()
            try:
                self.gcs_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.gcs_proc.kill()
