"""Shared constants used by both the control plane and client APIs."""

# Placement-group bundle strategies (ray: python/ray/util/placement_group.py
# `strategy` arg; src/ray/protobuf/common.proto PlacementStrategy).
PG_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")

# Placement-group lifecycle states.
PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_RESCHEDULING = "RESCHEDULING"
PG_REMOVED = "REMOVED"
