"""Object serialization: cloudpickle + out-of-band (pickle protocol 5) buffers.

Role-equivalent of the reference's SerializationContext (ray:
python/ray/_private/serialization.py:111).  Large contiguous buffers (numpy
arrays, jax host arrays, bytes) are carried out-of-band so they can be written
straight into shared memory without an extra copy, and reads off shared memory
are zero-copy memoryviews.

Wire layout of a serialized object:

    [u32 meta_len][meta pickle][u32 nbufs]
    ([u64 buf_len][buf bytes]) * nbufs

The metadata pickle references the buffers positionally via
pickle.PickleBuffer out-of-band serialization.
"""

from __future__ import annotations

import logging
import pickle
import struct
import sys
import types
from typing import Any, Callable, List, Optional, Sequence

import cloudpickle

logger = logging.getLogger(__name__)

_HEADER = struct.Struct("<I")
_BUFHDR = struct.Struct("<Q")
_BYTES_OOB_THRESHOLD = 64 * 1024

#: Copy-trace instrumentation for the single-pass put invariant (data
#: plane v2): write_into() bumps these once per call.  A put is one
#: serialize pass over the payload iff payload_bytes grows by exactly the
#: payload size per put — the deterministic check bench/tests pin instead
#: of trusting wall-clock (see tests/test_zz_dataplane.py).  Plain int
#: adds; nothing here allocates.
COPY_TRACE = {"writes": 0, "payload_bytes": 0, "meta_bytes": 0}


class SerializedObject:
    """A serialized object: one metadata pickle plus N out-of-band buffers.

    ``meta`` may be any bytes-like (the serializer hands over the pickle
    scratch as a memoryview — no intermediate ``bytes`` materialization on
    the put path); ``buffers`` are zero-copy views of the payload's large
    contiguous regions.  ``write_into`` is the ONE pass that touches
    payload bytes: headers, meta and every buffer are written straight
    into the destination (an arena reservation, a wire scratch) as
    vectored segment writes."""

    __slots__ = ("meta", "buffers", "_total")

    def __init__(self, meta, buffers: List[memoryview]):
        self.meta = meta
        self.buffers = buffers
        self._total = 0

    @property
    def total_bytes(self) -> int:
        n = self._total
        if n == 0:
            n = _HEADER.size + len(self.meta) + _HEADER.size
            for b in self.buffers:
                n += _BUFHDR.size + b.nbytes
            self._total = n
        return n

    def write_into(self, dest: memoryview) -> int:
        """Write wire format into `dest`; returns bytes written.  The
        single payload pass: each out-of-band buffer is memcpy'd exactly
        once, directly into the destination."""
        off = 0
        meta_len = len(self.meta)
        _HEADER.pack_into(dest, off, meta_len)
        off += _HEADER.size
        dest[off : off + meta_len] = self.meta
        off += meta_len
        _HEADER.pack_into(dest, off, len(self.buffers))
        off += _HEADER.size
        payload = 0
        for b in self.buffers:
            _BUFHDR.pack_into(dest, off, b.nbytes)
            off += _BUFHDR.size
            dest[off : off + b.nbytes] = b.cast("B") if b.format != "B" else b
            off += b.nbytes
            payload += b.nbytes
        COPY_TRACE["writes"] += 1
        COPY_TRACE["payload_bytes"] += payload
        COPY_TRACE["meta_bytes"] += meta_len
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_bytes)
        self.write_into(memoryview(out))
        return bytes(out)


def _restore_numpy(a):
    return a


def _restore_ext_ndarray(dtype, shape, buf):
    """Rebuild an extension-dtype ndarray from its out-of-band buffer.

    Zero-copy like numpy's builtin-dtype pickle-5 restore: the array is
    a read-only view over the buffer (whose .base chain keeps an arena
    pin alive on the shm zero-copy path)."""
    import numpy as np

    return np.frombuffer(buf, dtype=dtype).reshape(shape)


def _restore_arrow_table(buf):
    import pyarrow as pa

    return pa.ipc.open_stream(pa.py_buffer(buf)).read_all()


_by_value_checked: set = set()


def _maybe_register_by_value(module_name: Optional[str]) -> None:
    """Serialize functions/classes from *user-code* modules by value.

    A module-level function defined in the driver's own script or test file
    pickles by reference under plain cloudpickle, and the worker — which
    does not share the driver's sys.path — fails with ModuleNotFoundError.
    The reference solves this with working_dir runtime envs; the more
    robust default here is: any module that is not installed (not under
    sys.prefix/site-packages or the stdlib) and is not the framework
    itself is registered with cloudpickle's pickle-by-value registry, so
    its code travels with the task (ray: python/ray/_private/
    serialization.py role; cloudpickle.register_pickle_by_value).
    """
    if not module_name or module_name in _by_value_checked:
        return
    _by_value_checked.add(module_name)
    if module_name in ("__main__", "__mp_main__"):  # already by-value
        return
    if module_name.split(".", 1)[0] == "ray_tpu":
        return
    mod = sys.modules.get(module_name)
    f = getattr(mod, "__file__", None)
    if mod is None or f is None:  # builtin / namespace pkg
        return
    import os
    import site
    import sysconfig

    path = os.path.abspath(f)
    roots = {
        sysconfig.get_paths().get(k)
        for k in ("stdlib", "platstdlib", "purelib", "platlib")
    }
    try:  # user site + any system site-packages a venv exposes
        roots.update(site.getsitepackages())
        roots.add(site.getusersitepackages())
    except Exception:  # site may be absent under some embedded interpreters
        pass
    roots.discard(None)
    if any(path.startswith(os.path.abspath(r) + os.sep) for r in roots):
        return  # installed package: importable on workers by reference
    try:
        cloudpickle.register_pickle_by_value(mod)
    except Exception as e:
        logger.warning(
            "could not register module %r for by-value pickling (%s); "
            "functions from it will pickle by reference and workers "
            "without it on sys.path will fail to import it",
            module_name,
            e,
        )


class _Pickler(cloudpickle.CloudPickler):
    """Cloudpickle with isinstance-based custom reducers (handles jax.Array
    subclasses anywhere inside a container graph)."""

    def __init__(self, file, custom_reducers, **kw):
        super().__init__(file, **kw)
        self._custom = custom_reducers

    def reducer_override(self, obj):
        jax = sys.modules.get("jax")
        if jax is not None and isinstance(obj, jax.Array):
            import numpy as np

            return (_restore_numpy, (np.asarray(obj),))
        np_mod = sys.modules.get("numpy")
        if np_mod is not None and isinstance(obj, np_mod.ndarray):
            d = obj.dtype
            # Extension-dtype arrays (ml_dtypes bfloat16/fp8 — every jax
            # bf16 activation converted for the wire): numpy's own
            # protocol-5 reduce covers only builtin dtypes, so these
            # would serialize via tobytes() INTO the meta pickle — a
            # full extra payload copy the put path never sees.  Route
            # large contiguous ones out-of-band ourselves.
            if (
                d.isbuiltin != 1          # 2 = user-registered (ml_dtypes)
                and not d.hasobject
                and obj.flags.c_contiguous
                and obj.nbytes >= _BYTES_OOB_THRESHOLD
            ):
                # extension dtypes refuse the buffer protocol ("cannot
                # include dtype 'E' in a buffer") — export the raw
                # bytes through a zero-copy uint8 view instead
                return (
                    _restore_ext_ndarray,
                    (d, obj.shape,
                     pickle.PickleBuffer(obj.view(np_mod.uint8))),
                )
        pa = sys.modules.get("pyarrow")
        if pa is not None and isinstance(obj, pa.Table):
            # Arrow IPC, not arrow's own pickle: pickling a SLICED table
            # ships every chunk's entire parent buffer (a 1 MB slice of a
            # 25 MB block serializes as 25 MB; a shuffle reduce that
            # concats K slices ships K parents).  The IPC writer trims
            # buffers to the slice.  The payload rides out-of-band.
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, obj.schema) as w:
                w.write_table(obj)
            return (
                _restore_arrow_table,
                (pickle.PickleBuffer(sink.getvalue()),),
            )
        for typ, red in self._custom.items():
            if isinstance(obj, typ):
                return red(obj)
        if isinstance(obj, (types.FunctionType, type)):
            _maybe_register_by_value(getattr(obj, "__module__", None))
        return super().reducer_override(obj)


class _LargeBytes:
    """Wrapper that moves a big bytes/bytearray payload out-of-band.

    The C pickler serializes primitive bytes with a dedicated opcode
    BEFORE consulting reducer_override, embedding the payload in the
    metadata stream (a full extra copy through the put path) — so the
    top-level raw-buffer case (`put(b"...")`, ray's plasma raw-buffer
    analogue) is wrapped here instead.  Deserialization pays the one
    unavoidable copy (`bytes(buffer)` owns its memory).
    """

    __slots__ = ("data",)

    def __init__(self, data):
        self.data = data

    def __reduce_ex__(self, protocol):
        ctor = bytearray if isinstance(self.data, bytearray) else bytes
        return (ctor, (pickle.PickleBuffer(self.data),))


# value types safe to memoize by (type, value): immutable, hashable, and
# equality implies identical wire bytes.  float is EXCLUDED on purpose:
# -0.0 == 0.0 would alias two different payloads, and NaN keys never hit.
_MEMO_TYPES = frozenset((int, str, bytes, bool, type(None)))
_MEMO_MAX_VALUE_LEN = 512   # memoized str/bytes size cap
_MEMO_MAX_ENTRIES = 4096


class SerializationContext:
    """Pickles python objects with out-of-band buffer extraction."""

    def __init__(self):
        self._custom_reducers = {}
        # (type, value) -> wire bytes for small immutable arguments that
        # repeat across task submissions (spec-template arg memo)
        self._small_memo: dict = {}

    def register_reducer(self, typ: type, reducer: Callable) -> None:
        self._custom_reducers[typ] = reducer

    def serialize_small(self, obj: Any) -> Optional[bytes]:
        """Memoized wire bytes for a small immutable value, or None when
        the value is not memoizable (caller falls back to serialize()).
        Repeated small args (status strings, small ints, flags) then cost
        one dict hit per submission instead of a pickle pass."""
        t = type(obj)
        if t not in _MEMO_TYPES:
            return None
        if (t is str or t is bytes) and len(obj) > _MEMO_MAX_VALUE_LEN:
            return None
        key = (t, obj)
        b = self._small_memo.get(key)
        if b is None:
            b = self.serialize(obj).to_bytes()
            if len(self._small_memo) >= _MEMO_MAX_ENTRIES:
                self._small_memo.clear()
            self._small_memo[key] = b
        return b

    def serialize(self, obj: Any) -> SerializedObject:
        import io

        if (
            isinstance(obj, (bytes, bytearray))
            and len(obj) >= _BYTES_OOB_THRESHOLD
        ):
            obj = _LargeBytes(obj)
        buffers: List[memoryview] = []

        def cb(pb: pickle.PickleBuffer):
            buffers.append(pb.raw())
            return False  # buffer handled out-of-band

        meta_io = io.BytesIO()
        pickler = _Pickler(
            meta_io, self._custom_reducers, protocol=5, buffer_callback=cb
        )
        pickler.dump(obj)
        # getbuffer, not getvalue: the meta pickle is handed over as a view
        # of the scratch (which the view keeps alive) — the put path then
        # writes it straight into the arena reservation instead of paying
        # a bytes materialization first (RT115 bytes-copy-on-hot-path)
        return SerializedObject(meta_io.getbuffer(), buffers)

    def deserialize(
        self, data: memoryview | bytes, owner: Any = None
    ) -> Any:
        """Reconstruct an object; out-of-band buffers come back as views
        into ``data``.

        With ``owner`` set (the shm-store zero-copy path), every
        out-of-band buffer is wrapped in an :class:`_OwnedBuffer` that
        keeps ``owner`` (a PinnedBuffer) alive through the consumer's
        base chain — e.g. an ndarray's ``.base`` — so the store cannot
        evict or reuse the range while any deserialized view survives
        (ray: plasma client pins mapped objects until the last Buffer
        is destructed, plasma/client.cc)."""
        if owner is not None and not SUPPORTS_ZEROCOPY_OWNER:
            raise RuntimeError(
                "zero-copy deserialize (owner=) requires CPython >= 3.12 "
                "(PEP 688 __buffer__); gate callers on "
                "serialization.SUPPORTS_ZEROCOPY_OWNER"
            )
        mv = memoryview(data)
        off = 0
        (meta_len,) = _HEADER.unpack_from(mv, off)
        off += _HEADER.size
        meta = mv[off : off + meta_len]
        off += meta_len
        (nbufs,) = _HEADER.unpack_from(mv, off)
        off += _HEADER.size
        buffers: List[Any] = []
        for _ in range(nbufs):
            (blen,) = _BUFHDR.unpack_from(mv, off)
            off += _BUFHDR.size
            b = mv[off : off + blen]
            buffers.append(b if owner is None else _OwnedBuffer(b, owner))
            off += blen
        # pickle.loads accepts any buffer: parsing the meta view in place
        # saves a bytes copy per get (objects created during the parse own
        # their memory, so nothing retains the view past the call)
        return pickle.loads(meta, buffers=buffers)


# PEP 688 ``__buffer__`` is honored by CPython >= 3.12 only; on older
# interpreters _OwnedBuffer would raise TypeError inside pickle, so
# callers gate the owner= zero-copy path on this and fall back to a copy.
SUPPORTS_ZEROCOPY_OWNER = sys.version_info >= (3, 12)


class _OwnedBuffer:
    """A buffer-protocol view that keeps an owner object alive.

    memoryview slices reference the bottom exporter (the arena mmap),
    not the pin that blocks eviction — so zero-copy deserialization
    routes buffers through this wrapper instead (PEP 688 ``__buffer__``,
    Python ≥3.12).  A consumer such as ``np.frombuffer`` records the
    wrapper as ``.base``, chaining the pin to the array's lifetime.
    """

    __slots__ = ("_view", "_owner")

    def __init__(self, view: memoryview, owner: Any):
        self._view = view
        self._owner = owner

    def __buffer__(self, flags):
        return memoryview(self._view)

    def __len__(self):
        return self._view.nbytes


_default_context: Optional[SerializationContext] = None


def get_context() -> SerializationContext:
    global _default_context
    if _default_context is None:
        _default_context = SerializationContext()
    return _default_context


def serialize(obj: Any) -> SerializedObject:
    return get_context().serialize(obj)


def deserialize(data: memoryview | bytes) -> Any:
    return get_context().deserialize(data)
