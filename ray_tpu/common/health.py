"""Adaptive (phi-accrual) failure detection for the GCS health plane.

Role-equivalent of the reference's GcsHealthCheckManager (ray:
src/ray/gcs/gcs_server/gcs_health_check_manager.h) upgraded from a
fixed `last_heartbeat + timeout` boolean to an accrual detector in the
style of Hayashibara et al. ("The phi Accrual Failure Detector", SRDS
2004, the detector Akka/Cassandra ship): each node's inter-heartbeat
intervals feed a rolling window, and the *suspicion level*

    phi(t_now) = -log10( P(interval > t_now - t_last) )

is computed against the observed interval distribution instead of a
wall-clock constant.  A loaded node whose heartbeats stretch from
100 ms to 200 ms raises phi slowly (the history absorbs the new
normal); a partitioned node's phi climbs without bound.  Consumers map
phi onto a three-state machine:

    ALIVE    phi <  phi_suspect
    SUSPECT  phi >= phi_suspect   (deprioritized, nothing killed)
    DEAD     phi >= phi_death     (confirmed: fencing + recovery fire)

Two wall-clock guards bound the adaptive band (see gcs.py):
``node_death_timeout_s`` stays the hard cap (silence past it is death
regardless of history — detection latency never regresses vs the fixed
detector), and ``health_death_floor_frac`` of it is the floor (a CI
box stalling the whole process for a second must not mass-kill nodes
whose learned interval was 100 ms).

The distribution model is a normal tail with a floored standard
deviation (``min_std_frac`` x mean): a floor is what keeps a
metronome-regular heartbeat history (std ~ 0) from exploding phi on
the first 2x-late beat — the exact false-positive mode this detector
exists to remove.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

__all__ = ["PhiAccrualDetector", "death_confirmed", "is_suspect"]

_SQRT2 = math.sqrt(2.0)
_LN10 = math.log(10.0)


class PhiAccrualDetector:
    """Per-node inter-heartbeat history + suspicion level.

    Not thread-safe by design: lives on the GCS event loop.  O(1) per
    heartbeat (rolling sum / sum-of-squares over a bounded window).
    """

    __slots__ = (
        "window", "min_std_frac", "min_samples",
        "_intervals", "_sum", "_sumsq", "_last",
    )

    def __init__(
        self,
        window: int = 64,
        min_std_frac: float = 0.35,
        min_samples: int = 5,
    ):
        self.window = max(2, int(window))
        self.min_std_frac = float(min_std_frac)
        self.min_samples = max(2, int(min_samples))
        self._intervals: deque = deque()
        self._sum = 0.0
        self._sumsq = 0.0
        self._last: Optional[float] = None

    # ---- recording -----------------------------------------------------
    def heartbeat(self, now: float) -> None:
        """Record one heartbeat arrival at monotonic time ``now``."""
        last = self._last
        self._last = now
        if last is None:
            return
        iv = now - last
        if iv <= 0.0:
            iv = 1e-9  # same-tick duplicates: keep the math finite
        self._intervals.append(iv)
        self._sum += iv
        self._sumsq += iv * iv
        if len(self._intervals) > self.window:
            old = self._intervals.popleft()
            self._sum -= old
            self._sumsq -= old * old

    # ---- queries -------------------------------------------------------
    @property
    def last_heartbeat(self) -> Optional[float]:
        return self._last

    def ready(self) -> bool:
        """Enough history for the adaptive verdict (before this, callers
        fall back to the fixed timeout)."""
        return len(self._intervals) >= self.min_samples

    def mean(self) -> float:
        n = len(self._intervals)
        return self._sum / n if n else 0.0

    def std(self) -> float:
        n = len(self._intervals)
        if n < 2:
            return 0.0
        m = self._sum / n
        var = self._sumsq / n - m * m
        return math.sqrt(var) if var > 0.0 else 0.0

    def phi(self, now: float) -> float:
        """Suspicion level at ``now``: 0 when a heartbeat just arrived /
        history is insufficient, growing without bound with silence."""
        if self._last is None or not self.ready():
            return 0.0
        elapsed = now - self._last
        m = self.mean()
        std = max(self.std(), self.min_std_frac * m, 1e-9)
        z = (elapsed - m) / std
        if z <= 0.0:
            return 0.0
        # phi = -log10(P(X > elapsed)), X ~ N(mean, std)
        p = 0.5 * math.erfc(z / _SQRT2)
        if p > 1e-300:
            return -math.log10(p)
        # erfc underflowed: asymptotic tail  P ~ pdf(z)/z
        return (z * z / 2.0 + math.log(z * math.sqrt(2.0 * math.pi))) / _LN10


def death_confirmed(phi: float, elapsed: float,
                    phi_death: float, floor_s: float, cap_s: float) -> bool:
    """The ONE death rule (GCS health loop and the failure_detection
    bench share it): phi past the death threshold with at least
    ``floor_s`` of silence, or silence past the hard cap ``cap_s``
    regardless of phi."""
    return (phi >= phi_death and elapsed >= floor_s) or elapsed > cap_s


def is_suspect(phi: float, phi_suspect: float) -> bool:
    return phi >= phi_suspect
