"""Resource accounting with fixed-point arithmetic.

Role-equivalent of the reference's ResourceSet/FixedPoint (ray:
src/ray/common/scheduling/resource_set.h:31, fixed_point.h) redesigned for a
TPU cluster: besides the scalar resources ("CPU", "memory",
"object_store_memory"), TPU capacity is modelled as

  - ``TPU``                 — number of chips on the host
  - ``TPU-<gen>`` (e.g. TPU-v5e)  — generation-tagged chip count
  - ``<slice_name>``        — 1.0 on every host of a named slice (gang affinity)
  - ``TPU-<topology>-head`` — 1.0 only on worker 0 of a slice (coordinator
                              election for SPMD groups; mirrors the semantics
                              of ray: python/ray/_private/accelerators/tpu.py:376-397)

All quantities are stored as integers in units of 1/10000 so fractional
requests (e.g. {"CPU": 0.5}) compose exactly.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping

GRANULARITY = 10000

TPU_RESOURCE = "TPU"
CPU_RESOURCE = "CPU"
GPU_RESOURCE = "GPU"
MEMORY_RESOURCE = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

# Resources where fractional allocation of a single unit makes no sense and a
# request > 1 must be an integer (mirrors the reference's UNIT_INSTANCE set).
UNIT_INSTANCE_RESOURCES = {TPU_RESOURCE, GPU_RESOURCE}


def _to_fixed(v: float) -> int:
    fp = round(v * GRANULARITY)
    if fp < 0:
        raise ValueError(f"negative resource quantity: {v}")
    if fp == 0 and v > 0:
        raise ValueError(
            f"resource quantity {v} is below the minimum granularity "
            f"of {1 / GRANULARITY}"
        )
    return fp


class ResourceSet:
    """A bag of named resource quantities (fixed-point)."""

    __slots__ = ("_fp",)

    def __init__(self, quantities: Mapping[str, float] | None = None, *, _fp=None):
        if _fp is not None:
            self._fp: Dict[str, int] = {k: v for k, v in _fp.items() if v > 0}
        else:
            self._fp = {}
            for k, v in (quantities or {}).items():
                fp = _to_fixed(v)
                if fp > 0:
                    self._fp[k] = fp

    # -- queries ---------------------------------------------------------
    def get(self, name: str) -> float:
        return self._fp.get(name, 0) / GRANULARITY

    def keys(self) -> Iterable[str]:
        return self._fp.keys()

    def is_empty(self) -> bool:
        return not self._fp

    def to_dict(self) -> Dict[str, float]:
        return {k: v / GRANULARITY for k, v in self._fp.items()}

    def covers(self, demand: "ResourceSet") -> bool:
        """True if every demanded quantity is available here."""
        for k, v in demand._fp.items():
            if self._fp.get(k, 0) < v:
                return False
        return True

    def utilization(self, total: "ResourceSet") -> float:
        """Max fractional utilization across resources present in `total`,
        treating self as the *available* amount. Used by the scheduler's
        binpack/spread scoring."""
        worst = 0.0
        for k, cap in total._fp.items():
            if cap <= 0:
                continue
            avail = self._fp.get(k, 0)
            used = (cap - avail) / cap
            worst = max(worst, used)
        return worst

    # -- arithmetic ------------------------------------------------------
    def add(self, other: "ResourceSet") -> "ResourceSet":
        fp = dict(self._fp)
        for k, v in other._fp.items():
            fp[k] = fp.get(k, 0) + v
        return ResourceSet(_fp=fp)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        """Subtract; raises if it would go negative."""
        fp = dict(self._fp)
        for k, v in other._fp.items():
            nv = fp.get(k, 0) - v
            if nv < 0:
                raise ValueError(f"resource {k} would go negative")
            fp[k] = nv
        return ResourceSet(_fp=fp)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._fp == other._fp

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (_resource_set_from_fp, (dict(self._fp),))


def _resource_set_from_fp(fp):
    return ResourceSet(_fp=fp)


def validate_task_resources(res: Mapping[str, float]) -> None:
    for k, v in res.items():
        if v < 0:
            raise ValueError(f"resource {k!r} quantity must be >= 0, got {v}")
        if k in UNIT_INSTANCE_RESOURCES and v > 1 and v != int(v):
            raise ValueError(
                f"{k} request must be an integer when > 1 (got {v}); "
                "fractional requests are only allowed for a single unit"
            )
