"""Central config/flag registry.

Role-equivalent of the reference's RAY_CONFIG registry (ray:
src/ray/common/ray_config_def.h — 218 flags overridable via env vars), done
the Python way: one declarative table, values overridable via ``RT_<NAME>``
environment variables, importable everywhere as ``from ray_tpu.common.config
import cfg``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict


def _parse_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


class _Config:
    _DEFS: Dict[str, tuple[type, Any]] = {}

    def __init__(self):
        self._values: Dict[str, Any] = {}

    @classmethod
    def define(cls, name: str, typ: type, default: Any) -> None:
        cls._DEFS[name] = (typ, default)

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._DEFS:
            raise AttributeError(f"unknown config flag: {name}")
        if name not in self._values:
            typ, default = self._DEFS[name]
            env = os.environ.get(f"RT_{name.upper()}")
            if env is None:
                self._values[name] = default
            elif typ is bool:
                self._values[name] = _parse_bool(env)
            elif typ in (dict, list):
                self._values[name] = json.loads(env)
            else:
                self._values[name] = typ(env)
        return self._values[name]

    def override(self, name: str, value: Any) -> None:
        if name not in self._DEFS:
            raise AttributeError(f"unknown config flag: {name}")
        self._values[name] = value

    def reset(self) -> None:
        self._values.clear()


D = _Config.define

# --- wire protocol / rpc ---
D("rpc_max_frame_bytes", int, 512 * 1024 * 1024)
# per-tick frame coalescing: messages queued on one connection within a
# single event-loop tick ride one BATCH frame; a burst past this count
# flushes mid-tick so send_backlog policing sees the bytes
D("rpc_batch_max_msgs", int, 128)
# ...and a byte cap on the same accumulator: coalescing must never build
# a frame the peer rejects (rpc_max_frame_bytes), so ticks carrying large
# payloads (object chunks, big inline args) flush in small groups
D("rpc_batch_max_bytes", int, 8 * 1024 * 1024)
# flush window for buffered object-directory GCS notifications
# (add_object_location & co.): non-urgent announces wait up to this long
# (or gcs_notify_flush_max entries) for one batched rpc; any ref export
# or local get-miss flushes immediately (visibility unchanged)
D("gcs_notify_flush_window_s", float, 0.01)
D("gcs_notify_flush_max", int, 64)
D("rpc_connect_timeout_s", float, 30.0)
D("rpc_call_timeout_s", float, 120.0)
D("heartbeat_interval_s", float, 1.0)
D("node_death_timeout_s", float, 10.0)

# --- adaptive failure detection (common/health.py phi-accrual detector;
# reference role: GcsHealthCheckManager) ---
# suspicion level (phi = -log10 P(silence)) at which a node enters
# SUSPECT: deprioritized for new leases / pulls / serve routing, but
# nothing is killed, reformed, or restarted
D("health_phi_suspect", float, 3.0)
# suspicion level that CONFIRMS death (with the wall-clock floor/cap
# below): recovery machinery (fencing, actor restart, reform) fires
D("health_phi_death", float, 8.0)
# rolling inter-heartbeat history window per node
D("health_window", int, 64)
# std-deviation floor as a fraction of the mean interval: keeps a
# metronome-regular history (std ~ 0) from exploding phi on the first
# late beat — the dominant false-positive mode of accrual detectors
D("health_min_std_frac", float, 0.35)
# heartbeats of history required before the adaptive verdict applies
# (below it, the fixed node_death_timeout_s path decides alone)
D("health_min_samples", int, 5)
# wall-clock death FLOOR as a fraction of node_death_timeout_s: phi can
# confirm death no earlier than this much silence (a whole-process GC /
# CPU stall on the GCS host must not mass-kill fast-heartbeat nodes);
# node_death_timeout_s itself remains the hard CAP regardless of phi
D("health_death_floor_frac", float, 0.5)
# how long clients (raylets, drivers, workers) keep re-dialing a dead GCS
# before declaring the cluster lost
D("gcs_reconnect_max_downtime_s", float, 60.0)
# debounce for GCS snapshot flushes (fault-tolerance checkpoint)
# Snapshot compaction cadence.  Durability does NOT ride this: critical
# mutations are WAL-appended before their ack (see CheckpointStore), so a
# longer debounce only lengthens the WAL replayed at restart — while each
# snapshot pickles the full control-plane state, which at high PG/actor
# churn was ~15% of GCS CPU at 50 ms.
D("gcs_checkpoint_debounce_s", float, 0.25)
# how often each process ships its util.metrics registry to the GCS
D("metrics_push_interval_s", float, 5.0)
# node-to-node object transfer: chunk size + pipelined chunks in flight
# (ray analogue: object_manager 64MB chunks / ObjectBufferPool)
D("transfer_chunk_bytes", int, 8 * 1024 * 1024)
D("transfer_inflight_chunks", int, 4)
# timeline ring size per process (api.timeline())
D("timeline_max_events", int, 10_000)

# --- object store ---
D("object_store_bytes", int, 0)  # 0 = auto (30% of /dev/shm free, capped)
D("object_store_auto_cap_bytes", int, 8 * 1024 * 1024 * 1024)
D("inline_object_max_bytes", int, 100 * 1024)  # small results ride the RPC reply
# get() of a shm object this large deserializes zero-copy off the arena
# (pinned, read-only views) instead of copying out (plasma mmap-read role)
D("zerocopy_get_min_bytes", int, 1024 * 1024)
# put-side inline fast path (data plane v2): serialized payloads up to
# this size land in a per-process slab of pre-registered, pre-faulted
# arena slots — one shard-lock publish instead of a create/seal round
# trip.  0 disables the slab (every put rides the create path).
D("put_inline_max_bytes", int, 16 * 1024)
# slots reserved per slab refill batch (one allocator-lock acquisition +
# one touch-ahead pass amortized across the whole batch); the C-side
# per-client ledger caps total reserved slots at rt_store_max_slab_slots
D("put_inline_slab_slots", int, 32)
D("object_chunk_bytes", int, 16 * 1024 * 1024)  # node-to-node transfer chunk

# --- pip runtime envs (reference: runtime_env/pip.py role)
D("pip_env_install_timeout_s", float, 600.0)
# conda executable for conda runtime envs ("" = auto: conda/mamba/
# micromamba on PATH); container runtime for container runtime envs
# ("" = auto: podman/docker on PATH)
D("conda_exe", str, "")
D("container_runtime", str, "")

# --- runtime collectives (util/collective; reference: ray.util.collective)
# per-hop transfer chunk size — the named knob the selection layer and
# the bench matrix sweep; GroupOptions.chunk_bytes overrides per group
D("collective_chunk_bytes", int, 4 * 1024 * 1024)  # ring transfer chunk
# messages at or under this ride the latency-optimal algorithms when
# selection is on (auto): recursive doubling for allreduce (pow2
# worlds), binomial tree for broadcast
D("collective_small_max_bytes", int, 64 * 1024)
# elements per quantization block for the int8 wire codec (per-block
# f32 absmax scale + int8 payload; quantize.py)
D("collective_quant_block", int, 2048)
# how stale the cached SUSPECT-node set may get before the algorithm
# selection layer re-reads node_health (0 disables the health input)
D("collective_suspect_refresh_s", float, 1.0)
# co-hosted ranks hand chunks through the shm arena past this size
# (below it, the pickle5 oob-buffer wire path is cheaper than an
# arena create/seal/delete round trip)
D("collective_shm_min_bytes", int, 64 * 1024)
D("collective_op_timeout_s", float, 120.0)  # per-wait peer-traffic budget
D("collective_rendezvous_timeout_s", float, 60.0)
# podracer plane: abort a run() that made no sufficient progress in this
# window (a wedged fleet must surface as an error, not a silent hang)
D("podracer_progress_timeout_s", float, 300.0)
# podracer learner queue cap as a multiple of batch_fragments (beyond
# it the oldest queued fragment is shed — backpressure on sampling
# transiently outpacing training)
D("podracer_queue_factor", int, 4)
# peer-conn loss on a SUSPECT node defers poisoning until the GCS
# confirms the node's fate (dead -> poison, recovered -> no-op); this
# bounds the wait (unresolved past it poisons — fail-safe), with
# collective_confirm_poll_s the re-check cadence
D("collective_confirm_death_timeout_s", float, 15.0)
D("collective_confirm_poll_s", float, 0.25)

# --- streaming generator returns (reference: num_returns="streaming")
D("streaming_backpressure_items", int, 64)  # unacked items before the
#   producing worker pauses the generator

# --- object spilling (reference role: local_object_manager + external_storage)
D("object_spill_enabled", int, 1)
D("object_spill_high_frac", float, 0.8)  # arena fill ratio that triggers spill
D("object_spill_low_frac", float, 0.6)   # spill until back under this ratio
D("object_spill_max_restore_bytes", int, 0)  # 0 = no cap on restore size

# --- scheduler ---
D("sched_spread_threshold", float, 0.5)
# pending-lease wake scan: max non-placeable requests scanned (rotated
# to the tail) and max waiters woken per pass — bounds each pass at
# O(window) instead of O(backlog); grant-chaining re-kicks keep large
# capacity releases draining
D("sched_kick_scan_window", int, 64)
# actor-push flow control: once a connection's unsent transport buffer
# exceeds this, submissions queue behind the pump's drain() await
# instead of buffering unboundedly via call_soon
D("rpc_send_backlog_limit_bytes", int, 1 << 20)
D("sched_max_pending_lease_s", float, 60.0)
# in-flight lease requests per scheduling class: requests beyond this
# just park at the GCS (it grants as capacity frees and every grant
# re-pumps), while each parked request costs a call's coroutine/future
# machinery — unbounded, a 1000-deep task window parked ~1000 of them
D("sched_max_lease_requests_per_class", int, 16)
D("worker_pool_prestart", int, 0)
D("worker_idle_timeout_s", float, 300.0)
D("max_tasks_in_flight_per_worker", int, 1)  # >1 pipelines (uniform tasks)

# --- workers ---
D("worker_start_timeout_s", float, 60.0)
D("worker_nice", int, 0)

# --- logging / observability ---
D("log_dir", str, "")  # empty = <session_dir>/logs
D("event_buffer_size", int, 10000)
D("metrics_export_interval_s", float, 5.0)

# --- accelerators ---
D("tpu_chips_override", int, -1)  # -1 = autodetect
D("tpu_topology_override", str, "")

# --- task execution ---
D("task_max_retries_default", int, 3)
D("actor_max_restarts_default", int, 0)

# --- data streaming ---
D("data_streaming_window", int, 8)  # max blocks in production at once

# --- memory monitor (OOM protection) ---
D("memory_usage_threshold", float, 0.95)  # kill workers above this
D("memory_monitor_interval_s", float, 1.0)  # 0 disables the monitor
D("memory_monitor_kill_grace_s", float, 3.0)  # min spacing between kills
D("memory_monitor_fake_usage_file", str, "")  # test override

# --- workflows ---
D("workflow_storage", str, "/tmp/ray_tpu/workflows")

# --- refcounting / lineage ---
D("ref_flush_interval_s", float, 0.05)  # batch window for holder updates
D("lineage_reconstruction_max", int, 3)  # re-executions per lost task
D("gcs_free_delay_s", float, 0.5)  # grace before freeing unreferenced objects

# --- retry/backoff (common/backoff.py: the one shared exponential-
# backoff-with-jitter policy; every knob below parameterizes a call
# site of it — no retry loop hand-rolls its own schedule, rtlint RT112
# flags the unbounded-no-backoff shape) ---
D("backoff_base_s", float, 0.05)
D("backoff_mult", float, 2.0)
D("backoff_max_s", float, 2.0)
D("backoff_jitter_frac", float, 0.1)
# client-side object pull retries in Runtime._resolve_one (previously
# the literal `failed_pulls < 8` and `sleep(min(0.2*n, 2.0))` ladder)
D("pull_retry_max", int, 8)
D("pull_retry_base_s", float, 0.2)
D("pull_retry_max_s", float, 2.0)
# failed pulls tolerated before an infinite-deadline wait (ray_tpu.wait)
# surfaces the object as lost (was a literal 4)
D("pull_retry_infinite_max", int, 4)
# deadline-bounded get() retry poll (was a literal asyncio.sleep(0.05))
D("get_retry_poll_s", float, 0.05)
# ReconnectingConnection dial loop (was 0.1 doubling to a literal 2.0)
D("reconnect_backoff_base_s", float, 0.1)
D("reconnect_backoff_max_s", float, 2.0)

# --- graceful drain / preemption (gcs.py drain protocol v2) ---
# default drain budget when the caller names none (idle autoscaler
# drains and preemption notices without an announced deadline)
D("drain_deadline_default_s", float, 30.0)
# concurrent evacuation pulls per draining node (each is a target-node
# pull_object of a sole-copy object)
D("drain_evac_concurrency", int, 8)
# share of the drain budget spent waiting for in-flight task leases to
# return before proceeding to the kill-adjacent phases
D("drain_lease_wait_frac", float, 0.5)
# raylet preemption-watcher poll cadence (node.preempt chaos site +
# the GCE metadata stub); 0 disables the watcher
D("preempt_poll_interval_s", float, 0.25)
# actor-migration state blobs at most this large ride inline over the
# worker conn into GCS KV (the original path, bit-for-bit); larger
# blobs (pipeline-stage params + optimizer state) are stored in the
# shm object plane and only the object id crosses the control plane
D("actor_ckpt_inline_max_bytes", int, 256 * 1024)
# restore-side fetch budget for an object-plane checkpoint blob; on
# expiry the actor restarts fresh (the same degradation as a failed
# checkpoint capture) instead of wedging create_actor forever
D("actor_ckpt_fetch_timeout_s", float, 60.0)
# capture-fence quiescence budget: how long a drain checkpoint waits
# for already-admitted actor calls to finish before capturing anyway.
# A re-entrant call pattern (m1 awaiting self.m2 — rtflow RT201
# territory) can never quiesce once the fence parks the inner call; on
# expiry the capture proceeds (logged) rather than burning the whole
# drain deadline into the hard-death fallback
D("actor_ckpt_quiesce_timeout_s", float, 20.0)

cfg = _Config()
