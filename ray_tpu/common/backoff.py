"""The one shared retry policy: deadline-aware exponential backoff + jitter.

Before this module every retry loop hand-rolled its own shape — fixed
``asyncio.sleep(0.05)`` polls, ``delay = min(delay * 2, 2.0)`` ladders,
magic attempt caps like ``failed_pulls < 8`` — so hot-spin bugs and
thundering-herd reconnects had to be found one site at a time (rtlint
RT112 now flags the unbounded-no-backoff shape outright).  All retrying
paths (rpc reconnect, GCS resubscribe via the reconnect channel, object
pull retry, lease-pending resubmission, rendezvous polls) now share this
implementation; per-site parameters live as named ``common/config.py``
knobs.

Shape: ``delay(attempt) = min(base * mult^(attempt-1), max) * jitter``,
clamped to the remaining deadline.  Jitter is multiplicative
(``1 ± jitter_frac``) so simultaneous retriers de-correlate without
changing the expected schedule.

Usage::

    bo = Backoff(BackoffPolicy(base_s=0.1, max_s=2.0), deadline=deadline)
    while True:
        try:
            return await dial()
        except OSError:
            if not await bo.wait():   # budget (attempts or deadline) spent
                raise
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Optional

__all__ = ["Backoff", "BackoffPolicy"]


@dataclass(frozen=True)
class BackoffPolicy:
    """Immutable schedule parameters (share freely across call sites)."""

    base_s: float = 0.05
    mult: float = 2.0
    max_s: float = 2.0
    jitter_frac: float = 0.1   # delay *= uniform(1-j, 1+j)
    max_attempts: int = 0      # 0 = unbounded (a deadline governs instead)

    def delay_for(self, attempt: int, rng=None) -> float:
        """Nominal delay for the ``attempt``-th retry (1-based)."""
        try:
            d = self.base_s * (self.mult ** (attempt - 1))
        except OverflowError:
            # float pow overflows past ~2.0**1024 — a legitimately
            # long unbounded wait (no deadline, no attempt cap) must
            # keep backing off at the cap, not crash
            d = self.max_s
        if d > self.max_s:  # also clamps an inf from the multiply
            d = self.max_s
        if self.jitter_frac:
            j = self.jitter_frac
            d *= (rng.uniform(1.0 - j, 1.0 + j) if rng is not None
                  else random.uniform(1.0 - j, 1.0 + j))
        return d if d > 0.0 else 0.0


class Backoff:
    """Mutable retry state for ONE operation: attempt counter + deadline.

    ``deadline`` is a ``time.monotonic()`` instant (None or ``inf`` =
    no deadline); delays clamp to the remaining budget so the last sleep
    never overshoots it.  ``rng`` makes the jitter stream reproducible
    for deterministic tests.
    """

    __slots__ = ("policy", "deadline", "rng", "attempts")

    def __init__(self, policy: BackoffPolicy,
                 deadline: Optional[float] = None, rng=None):
        self.policy = policy
        self.deadline = deadline
        self.rng = rng
        self.attempts = 0

    def next_delay(self) -> Optional[float]:
        """The next sleep, or None when the budget (attempt cap or
        deadline) is spent — callers give up / surface their error."""
        self.attempts += 1
        p = self.policy
        if p.max_attempts and self.attempts > p.max_attempts:
            return None
        d = p.delay_for(self.attempts, self.rng)
        if self.deadline is not None:
            remaining = self.deadline - time.monotonic()
            if remaining <= 0:
                return None
            if d > remaining:
                d = remaining
        return d

    async def wait(self) -> bool:
        """Async sleep for the next delay; False when the budget is
        spent (nothing slept)."""
        d = self.next_delay()
        if d is None:
            return False
        await asyncio.sleep(d)
        return True

    def wait_sync(self) -> bool:
        """Blocking twin of :meth:`wait` for caller/executor threads
        (never the io loop — rtlint RT101 polices that)."""
        d = self.next_delay()
        if d is None:
            return False
        time.sleep(d)
        return True
