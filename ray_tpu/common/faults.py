"""Deterministic fault injection: named sites + seeded plans + chaos driver.

The runtime's recovery paths (lease breaks, pull retries, GCS restarts,
collective poisoning/re-formation) are only trustworthy if a failure can
be injected *at a named site, on a chosen hit, reproducibly*.  This
module is that plane:

- **Sites** are string-named hooks threaded through the hot paths
  (``rpc.send.frame``, ``rpc.recv.msg``, ``raylet.lease.grant``,
  ``store.put``, ``collective.peer_conn``, ``node.preempt`` — the
  raylet's preemption watcher polls the last one, so a seeded plan
  delivers a spot-termination notice deterministically, with
  ``delay_s`` carrying the announced drain deadline; the full registry
  is in docs/architecture.md).  ``store.put`` fires once per reserve
  attempt whichever sub-path serves it — the data-plane-v2 inline slab
  and the vectored create path hit the same
  ``ShmStore._put_fault_check`` the v1 ``create`` call guarded, so
  seeded put traces survived the rebuild bit-identically (pinned in
  test_zz_dataplane.py).  Each site guards itself with
  ``if faults.ACTIVE is not None:`` — with ``RT_FAULTS`` unset the hook
  is a single module-attribute None check: no allocation, no branch
  taken, pinned by an alloc assertion in test_taskplane_batching.py.

- **FaultPlan** selects when a site fires: exact ``site`` name, an
  optional ``match`` substring against the site's context string, an
  ``nth``-matching-hit window (``nth``/``count``) or a seeded
  probability ``p``.  Decisions consume a per-plan ``random.Random(seed)``
  only on *matching* hits, so the same plan over the same hit sequence
  fires identically — bit-for-bit — across runs.

- **Actions** are interpreted by the site: ``drop`` (message/frame
  vanishes), ``delay`` (re-delivered after ``delay_s``), ``dup``
  (delivered twice), ``error`` (the call fails with an injected
  RpcError / the store raises StoreFullError), ``reset`` (transport
  aborted), ``kill`` (the granted worker is hard-killed).

Activation: programmatic ``install(plans)`` in-process, or the
``RT_FAULTS`` environment variable carrying a JSON list of plan dicts —
the env form is inherited by raylet/worker/GCS subprocesses, so a test
can arm a fault inside a process it never touches directly.  Every
firing is recorded; ``trace()`` is the determinism contract tests
assert on.

``ChaosController`` is the driver-side half for process-level faults a
site hook cannot express (GCS kill/restart, whole-node kill) — it wraps
a ``cluster_utils.Cluster`` and logs every event it applies, so a chaos
schedule is replayable from its log.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "ACTIVE",
    "ChaosController",
    "FaultController",
    "FaultPlan",
    "LINKS_ACTIVE",
    "LOCAL_ENDPOINT",
    "SITES",
    "SITE_COLLECTIVE_P2P",
    "SITE_COLLECTIVE_PEER_CONN",
    "SITE_NODE_PREEMPT",
    "SITE_RAYLET_LEASE_GRANT",
    "SITE_RPC_RECV_MSG",
    "SITE_RPC_SEND_FRAME",
    "SITE_STORE_PUT",
    "clear",
    "cut_link",
    "heal_link",
    "install",
    "link_is_cut",
    "link_log",
    "plans_from_json",
    "plans_to_json",
    "set_local_endpoint",
    "trace",
]

ENV_VAR = "RT_FAULTS"

# The canonical injection-site registry.  Every runtime hit() call
# guards one of these names, the docs/architecture.md site table is
# asserted against this tuple in tests, and rtproto's RT404 flags any
# hit site, plan, or registry entry that drifts from the others.  Add a
# site here WHEN you add its runtime check — a registered-but-unchecked
# name arms plans that never fire.
SITE_RPC_SEND_FRAME = "rpc.send.frame"
SITE_RPC_RECV_MSG = "rpc.recv.msg"
SITE_STORE_PUT = "store.put"
SITE_RAYLET_LEASE_GRANT = "raylet.lease.grant"
SITE_NODE_PREEMPT = "node.preempt"
SITE_COLLECTIVE_PEER_CONN = "collective.peer_conn"
SITE_COLLECTIVE_P2P = "collective.p2p"

SITES = (
    SITE_RPC_SEND_FRAME,
    SITE_RPC_RECV_MSG,
    SITE_STORE_PUT,
    SITE_RAYLET_LEASE_GRANT,
    SITE_NODE_PREEMPT,
    SITE_COLLECTIVE_PEER_CONN,
    SITE_COLLECTIVE_P2P,
)


@dataclass(frozen=True)
class FaultPlan:
    """One deterministic fault: where, when, and what to inject.

    ``site``    exact injection-site name (see the registry in docs).
    ``action``  drop | delay | dup | error | reset | kill — interpreted
                by the site.  Firing is traced at SELECTION time, so
                keep the action matched to what the site implements
                (the registry in docs/architecture.md lists each
                site's supported actions); a selected-but-unsupported
                action is a no-op at the site yet still appears in
                ``trace()``.
    ``match``   optional substring the site's context string must
                contain for the hit to count (e.g. an rpc method name).
    ``nth``     1-based matching-hit number the window opens at.
    ``count``   how many consecutive matching hits fire from ``nth``.
    ``p``       when > 0, replaces the window: each matching hit at or
                past ``nth`` fires with probability ``p`` drawn from the
                plan's own ``random.Random(seed)`` stream.
    ``delay_s`` delay for ``action="delay"``.
    """

    site: str
    action: str = "error"
    match: Optional[str] = None
    nth: int = 1
    count: int = 1
    p: float = 0.0
    seed: int = 0
    delay_s: float = 0.05

    def to_dict(self) -> dict:
        d = {"site": self.site, "action": self.action, "nth": self.nth,
             "count": self.count, "seed": self.seed}
        if self.match is not None:
            d["match"] = self.match
        if self.p:
            d["p"] = self.p
        # any non-default delay_s round-trips: "delay" uses it as the
        # re-delivery lag, "preempt" as the announced drain deadline —
        # dropping it for non-delay actions silently rewrote a chaos
        # plan's deadline through plans_to_json/RT_FAULTS
        if self.action == "delay" or self.delay_s != type(self).delay_s:
            d["delay_s"] = self.delay_s
        return d

    _FIELDS = ("site", "action", "match", "nth", "count", "p", "seed",
               "delay_s")

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            # a typo'd field (e.g. "mach" for "match") silently widening
            # or disarming a plan makes the chaos test lie — fail loudly,
            # matching the RT_FAULTS malformed-plan contract
            raise ValueError(
                f"FaultPlan has no field(s) {sorted(unknown)}; "
                f"valid fields: {list(cls._FIELDS)}"
            )
        site = d.get("site")
        if site is not None and site not in SITES:
            # the wire path (RT_FAULTS env / scenario JSON) validates
            # against the canonical registry: a typo'd site arms a plan
            # that never fires, which is exactly a chaos test that lies.
            # Direct FaultPlan(...) construction stays free-form so
            # unit tests can use synthetic site names.
            raise ValueError(
                f"unknown fault site {site!r}; registered sites: "
                f"{list(SITES)}"
            )
        return cls(**{k: d[k] for k in cls._FIELDS if k in d})


class _Armed:
    """Mutable per-plan firing state (hit counter + seeded rng)."""

    __slots__ = ("plan", "hits", "rng")

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.hits = 0
        self.rng = random.Random(plan.seed)


class FaultController:
    """Evaluates every armed plan at each site hit; records firings.

    Thread-safe: hits arrive from io-loop threads and caller threads of
    every runtime in the process.  The lock is only ever taken while a
    controller is installed — the disabled path never reaches here.
    """

    def __init__(self, plans: Sequence[FaultPlan]):
        self._armed: List[_Armed] = [_Armed(p) for p in plans]
        self._trace: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def hit(self, site: str, ctx: str = "") -> Optional[FaultPlan]:
        """Register one hit at ``site``; returns the plan to apply (the
        first armed plan whose selector fires) or None.

        EVERY matching plan counts the hit (and, in ``p`` mode, draws
        from its rng) even when an earlier plan already fired — each
        plan's firing schedule is a pure function of the matching-hit
        sequence, independent of which other plans are armed."""
        fired: Optional[FaultPlan] = None
        with self._lock:
            for a in self._armed:
                plan = a.plan
                if plan.site != site:
                    continue
                if plan.match is not None and plan.match not in ctx:
                    continue
                a.hits += 1
                if plan.p > 0.0:
                    fire = a.hits >= plan.nth and a.rng.random() < plan.p
                else:
                    fire = plan.nth <= a.hits < plan.nth + plan.count
                if fire and fired is None:
                    fired = plan
                    self._trace.append({
                        "site": site,
                        "ctx": ctx,
                        "hit": a.hits,
                        "action": plan.action,
                        "ts": time.monotonic(),
                    })
        return fired

    def trace(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._trace]


# The one module-level switch every site checks.  None = disabled; the
# site guard is then a single LOAD + is-None test with zero allocations.
ACTIVE: Optional[FaultController] = None


def install(plans: Sequence[FaultPlan]) -> FaultController:
    """Arm ``plans`` in this process (replaces any prior controller;
    counters and trace start fresh)."""
    global ACTIVE
    ACTIVE = FaultController(plans)
    return ACTIVE


def clear() -> None:
    """Disarm fault injection in this process."""
    global ACTIVE
    ACTIVE = None


def trace() -> List[Dict[str, Any]]:
    """Firings recorded by the installed controller ([] when disabled)."""
    return ACTIVE.trace() if ACTIVE is not None else []


def plans_to_json(plans: Sequence[FaultPlan]) -> str:
    return json.dumps([p.to_dict() for p in plans])


def plans_from_json(text: str) -> List[FaultPlan]:
    return [FaultPlan.from_dict(d) for d in json.loads(text)]


def _activate_from_env() -> None:
    text = os.environ.get(ENV_VAR)
    if not text:
        return
    # a malformed plan must fail LOUDLY: chaos silently disabled by a
    # typo'd env var is a test that stops testing anything
    install(plans_from_json(text))


_activate_from_env()


# ---------------------------------------------------------------------------
# Network partitions: a directional link-cut registry
# ---------------------------------------------------------------------------
#
# A partition is NOT a FaultPlan: it is silence, not an error — frames
# between two logical endpoints simply stop arriving, in one direction
# or both, until the link heals.  The registry below is keyed by
# (src_endpoint, dst_endpoint); the rpc layer consults it at the
# ``rpc.link`` site (outbound in Connection._write_frames, inbound in
# Connection._dispatch_msg) whenever both endpoints of a connection are
# known.  Endpoints are logical names: "gcs" for the control plane, the
# node id hex for a raylet and every worker/driver attached to that
# node.  Cuts carry heal-after semantics (a monotonic deadline) so a
# scripted transient partition self-heals bit-reproducibly; every
# cut/heal (including auto-heals) is recorded in ``link_log()`` — the
# replayable half of the determinism contract.

#: (src, dst) -> monotonic heal deadline (math.inf = until heal_link)
_LINKS: Dict[tuple, float] = {}
_LINKS_LOCK = threading.Lock()
_LINK_LOG: List[Dict[str, Any]] = []

#: fast-path flag: when False the rpc.link site is one module-attr
#: load + branch (same zero-alloc discipline as ACTIVE)
LINKS_ACTIVE: bool = False

#: this process's logical endpoint ("gcs", a node id hex, ...); set
#: once by the process entrypoint (gcs/raylet/worker main, or
#: Runtime.connect for drivers).  None = unlabeled: never cut.
LOCAL_ENDPOINT: Optional[str] = None


def set_local_endpoint(name: str, force: bool = False) -> None:
    """Label this process for the link-cut site.  First writer wins
    unless ``force`` — an in-process Raylet/Runtime pair must not
    relabel the process its entrypoint already named."""
    global LOCAL_ENDPOINT
    if LOCAL_ENDPOINT is None or force:
        LOCAL_ENDPOINT = name


def cut_link(src: str, dst: str, duration_s: Optional[float] = None) -> None:
    """Cut the directional link src -> dst: frames from ``src`` to
    ``dst`` are dropped.  ``duration_s`` arms auto-heal after that many
    seconds; None cuts until ``heal_link``."""
    global LINKS_ACTIVE
    deadline = (
        float("inf") if duration_s is None
        else time.monotonic() + duration_s
    )
    with _LINKS_LOCK:
        _LINKS[(src, dst)] = deadline
        _LINK_LOG.append({"event": "cut", "src": src, "dst": dst,
                          "duration_s": duration_s,
                          "ts": time.monotonic()})
        LINKS_ACTIVE = True


def heal_link(src: Optional[str] = None, dst: Optional[str] = None) -> None:
    """Heal cut links: both endpoints named heals EXACTLY that one
    direction (src -> dst; the asymmetric-route scenarios depend on
    healing one leg of a bidirectional cut); one endpoint named heals
    every cut touching it; neither heals all."""
    global LINKS_ACTIVE
    with _LINKS_LOCK:
        for key in list(_LINKS):
            s, d = key
            if src is not None and dst is not None:
                match = (s, d) == (src, dst)
            elif src is not None:
                match = src in (s, d)
            elif dst is not None:
                match = dst in (s, d)
            else:
                match = True
            if match:
                del _LINKS[key]
                _LINK_LOG.append({"event": "heal", "src": s, "dst": d,
                                  "ts": time.monotonic()})
        if not _LINKS:
            LINKS_ACTIVE = False


def link_is_cut(src: Optional[str], dst: Optional[str]) -> bool:
    """True when frames src -> dst are currently dropped.  Auto-heals
    (and logs) cuts whose deadline lapsed — heal-after needs no timer."""
    global LINKS_ACTIVE
    if src is None or dst is None:
        return False
    with _LINKS_LOCK:
        deadline = _LINKS.get((src, dst))
        if deadline is None:
            return False
        if time.monotonic() >= deadline:
            del _LINKS[(src, dst)]
            _LINK_LOG.append({"event": "auto_heal", "src": src, "dst": dst,
                              "ts": time.monotonic()})
            if not _LINKS:
                LINKS_ACTIVE = False
            return False
        return True


def link_log() -> List[Dict[str, Any]]:
    """Ordered cut/heal/auto-heal events applied in this process."""
    with _LINKS_LOCK:
        return [dict(e) for e in _LINK_LOG]


def clear_links() -> None:
    """Drop every cut and the log (test teardown)."""
    global LINKS_ACTIVE
    with _LINKS_LOCK:
        _LINKS.clear()
        _LINK_LOG.clear()
        LINKS_ACTIVE = False


# ---------------------------------------------------------------------------
# ChaosController: driver-side process-level faults
# ---------------------------------------------------------------------------


@dataclass
class _ChaosEvent:
    event: str
    detail: dict = field(default_factory=dict)
    ts: float = 0.0


class ChaosController:
    """Scripted process-level chaos against a ``cluster_utils.Cluster``.

    Site hooks cover in-process faults; killing whole processes (the
    GCS, a raylet and its workers) is driven from here.  Every applied
    event is appended to ``log`` in order, so a chaos schedule is
    reproducible: same seed + same method sequence ⇒ same victims.
    """

    def __init__(self, cluster, seed: int = 0):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.log: List[dict] = []

    def _record(self, event: str, **detail) -> None:
        self.log.append({"event": event, "detail": detail,
                         "ts": time.monotonic()})

    def record_external(self, event: str, **detail) -> None:
        """Log a storm event applied by an outside driver (e.g. a
        spot-fleet preemption issued through the autoscaler's provider
        rather than through this controller) so the unified
        ``storm_log()`` still covers it."""
        self._record(event, **detail)

    def storm_log(self) -> List[Dict[str, Any]]:
        """The ONE replayable storm record: the controller's own event
        log, the link-cut log, and the fault-injection trace of this
        process, merged and monotonically ordered.

        Before this existed a composed chaos scenario recorded in three
        places with three schemas; attributing an availability dip to
        "the partition, not the lease fault" meant hand-joining them.
        Every entry is normalized to the pinned schema
        ``{"ts", "source", "event", "detail"}`` with ``source`` one of
        ``"chaos"`` (process-level events driven from here), ``"link"``
        (partition cut/heal/auto-heal), ``"fault"`` (site-hook firings —
        NOTE: only firings in THIS process; sites armed via RT_FAULTS in
        raylet/worker subprocesses trace in those processes).  ``ts`` is
        ``time.monotonic()`` of this process; entries sort by it, ties
        keep insertion order (stable sort)."""
        entries: List[Dict[str, Any]] = []
        for e in self.log:
            entries.append({
                "ts": e["ts"],
                "source": "chaos",
                "event": e["event"],
                "detail": dict(e["detail"]),
            })
        for e in link_log():
            detail = {k: v for k, v in e.items()
                      if k not in ("event", "ts")}
            entries.append({
                "ts": e.get("ts", 0.0),
                "source": "link",
                "event": e["event"],
                "detail": detail,
            })
        for e in trace():
            entries.append({
                "ts": e.get("ts", 0.0),
                "source": "fault",
                "event": e["action"],
                "detail": {"site": e["site"], "ctx": e["ctx"],
                           "hit": e["hit"]},
            })
        entries.sort(key=lambda e: e["ts"])
        return entries

    # -- GCS (head) faults ----------------------------------------------
    def kill_gcs(self) -> None:
        """kill -9 the control plane (clients hold ReconnectingConnections
        and must ride the outage)."""
        self.cluster.kill_gcs()
        self._record("gcs_kill")

    def restart_gcs(self, timeout: float = 30.0) -> None:
        """Restart the GCS on the same port/session dir; state restores
        from the WAL + checkpoint and clients re-attach."""
        self.cluster.restart_gcs(timeout=timeout)
        self._record("gcs_restart")

    def gcs_outage(self, down_s: float = 0.5, timeout: float = 30.0) -> None:
        """kill -9, hold the control plane down for ``down_s``, restart."""
        self.kill_gcs()
        time.sleep(down_s)
        self.restart_gcs(timeout=timeout)

    # -- node faults -----------------------------------------------------
    def _pick_node(self, node=None):
        if node is not None:
            return node
        pool = [n for n in self.cluster._nodes
                if n is not self.cluster.head_node]
        pool = pool or list(self.cluster._nodes)
        if not pool:
            raise RuntimeError("no nodes to kill")
        return self.rng.choice(pool)

    def preempt_node(self, node=None, deadline_s: float = 5.0,
                     kill: bool = True, poll_s: float = 0.1):
        """Deliver a spot-preemption notice to a node, then (``kill``)
        hard-kill it once its graceful drain settles or the deadline
        lapses — the full GCE preemption sequence, seeded and replayable
        (``node=None`` picks a seeded-random non-head victim).

        Returns ``(node, drain_state)`` where ``drain_state`` is the
        GCS's final drain verdict ("drained", "failed", "dead", ...)."""
        import asyncio

        from ray_tpu.core import rpc

        node = self._pick_node(node)

        async def drive():
            # one connection for the notice AND the whole status poll —
            # a fresh dial per 0.1 s poll would hammer the GCS's accept
            # path exactly while it is busy driving the drain
            conn = await rpc.connect(self.cluster.address,
                                     name="chaos->gcs")
            try:
                reply = await conn.call("drain_node", {
                    "node_id": node.node_id,
                    "reason": "preemption",
                    "deadline_s": deadline_s,
                })
                accepted = bool(
                    isinstance(reply, dict) and reply.get("accepted")
                )
                state = (
                    reply.get("state") if isinstance(reply, dict) else None
                )
                if not kill:
                    return accepted, state
                # the provider kills at the announced deadline
                # regardless; polling just shortens the wait when the
                # drain finishes early (and records what it achieved)
                end = time.monotonic() + deadline_s + 2.0
                while time.monotonic() < end:
                    st = await conn.call(
                        "get_drain_status", {"node_id": node.node_id}
                    ) or {}
                    state = st.get("state")
                    if state in ("drained", "failed", "dead", "unknown"):
                        break
                    await asyncio.sleep(poll_s)
                return accepted, state
            finally:
                await conn.close()

        accepted, state = asyncio.run(drive())
        self._record("node_preempt", node_id=node.node_id,
                     deadline_s=deadline_s, accepted=accepted)
        if not kill:
            return node, state
        self.cluster.remove_node(node, allow_graceful=False)
        self._record("node_kill", node_id=node.node_id, graceful=False,
                     drain_state=state)
        return node, state

    def kill_node(self, node=None, graceful: bool = False):
        """Kill a raylet (and its workers).  ``node=None`` picks a
        seeded-random victim among the non-head nodes (falling back to
        the head when it is the only node)."""
        node = self._pick_node(node)
        self.cluster.remove_node(node, allow_graceful=graceful)
        self._record("node_kill", node_id=node.node_id, graceful=graceful)
        return node

    # -- network partitions ----------------------------------------------
    def _endpoint_of(self, x) -> str:
        """Resolve a partition side to its logical endpoint: "gcs", a
        ClusterNode, or a node-id hex string."""
        if x == "gcs":
            return "gcs"
        nid = getattr(x, "node_id", None)
        return nid if nid is not None else str(x)

    def _chaos_call(self, address: str, method: str, payload: dict) -> bool:
        """One-shot rpc to a cluster process (best-effort: a process
        already dead just misses the install, which is what a real
        partition would do to it too)."""
        import asyncio

        from ray_tpu.core import rpc

        async def drive():
            conn = await rpc.connect(address, name="chaos->proc",
                                     timeout=5.0)
            try:
                return await conn.call(method, payload, timeout=5.0)
            finally:
                await conn.close()

        try:
            asyncio.run(drive())
            return True
        except Exception:
            return False

    def _broadcast_chaos(self, method: str, payload: dict) -> None:
        """Install a link-cut table change in EVERY cluster process:
        the GCS, each raylet (which fans out to its workers), and this
        process (the driver).  Installing a cut in an uninvolved
        process is harmless — the registry only matches by endpoint."""
        self._chaos_call(self.cluster.address, method, payload)
        for n in list(self.cluster._nodes):
            self._chaos_call(n.address, method, payload)
        # this (driver) process applies the change in-process
        if method == "chaos_partition":
            cut_link(payload["src"], payload["dst"],
                     payload.get("duration_s"))
        else:
            heal_link(payload.get("src"), payload.get("dst"))

    def partition(self, a, b="gcs",
                  duration_s: Optional[float] = None) -> tuple:
        """Cut the network between ``a`` and ``b`` in BOTH directions
        (a, b: ClusterNode, node-id hex, or "gcs").  Frames between the
        two endpoints — raylet<->GCS, worker<->GCS, raylet<->raylet
        transfers, driver<->worker pushes — are silently dropped (real
        partition semantics: silence, not errors) until ``heal()`` or
        the ``duration_s`` auto-heal.  Returns (endpoint_a, endpoint_b).

        A process spawned AFTER the cut does not inherit it (the
        registry is per-process state); partition before spawning, or
        re-issue."""
        ea, eb = self._endpoint_of(a), self._endpoint_of(b)
        for src, dst in ((ea, eb), (eb, ea)):
            self._broadcast_chaos(
                "chaos_partition",
                {"src": src, "dst": dst, "duration_s": duration_s},
            )
        self._record("partition", a=ea, b=eb, duration_s=duration_s)
        return ea, eb

    def cut(self, src, dst, duration_s: Optional[float] = None) -> tuple:
        """Directional half of partition(): only src -> dst frames drop
        (dst still reaches src) — the asymmetric-route failure mode."""
        es, ed = self._endpoint_of(src), self._endpoint_of(dst)
        self._broadcast_chaos(
            "chaos_partition",
            {"src": es, "dst": ed, "duration_s": duration_s},
        )
        self._record("cut", src=es, dst=ed, duration_s=duration_s)
        return es, ed

    def heal(self, a=None, b=None) -> None:
        """Heal partitions: both sides named heals that pair (BOTH
        directions — the inverse of partition()), one side heals every
        cut touching it, none heals everything.  Directional heals of a
        single leg go through ``heal_link`` on the target processes
        directly (the ``cut()`` twin)."""
        ea = self._endpoint_of(a) if a is not None else None
        eb = self._endpoint_of(b) if b is not None else None
        if ea is not None and eb is not None:
            # heal_link with both endpoints is exact-direction: undo
            # the bidirectional partition() install leg by leg
            for src, dst in ((ea, eb), (eb, ea)):
                self._broadcast_chaos("chaos_heal",
                                      {"src": src, "dst": dst})
        else:
            self._broadcast_chaos("chaos_heal", {"src": ea, "dst": eb})
        self._record("heal", a=ea, b=eb)
