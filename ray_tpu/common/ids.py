"""Unique identifiers for cluster entities.

Equivalent in role to the reference's ID types (ray: src/ray/common/id.h) but
designed fresh: every ID is a 16-byte value; the kind lives in the Python
type (and in message field position on the wire), not in the bytes.
ObjectIDs are *derived* from the producing TaskID plus a return index, which
keeps lineage reconstruction possible without a separate table (ray:
common/id.h ObjectID::FromIndex analogue).
"""

from __future__ import annotations

import hashlib
import os
import struct

_ID_LEN = 16  # bytes on the wire (kind lives in the Python type only)


class BaseID:
    """Immutable 16-byte identifier."""

    KIND = 0x00
    __slots__ = ("_bin",)

    def __init__(self, binary: bytes):
        if len(binary) != _ID_LEN:
            raise ValueError(
                f"{type(self).__name__} requires {_ID_LEN} bytes, got {len(binary)}"
            )
        self._bin = bytes(binary)

    @classmethod
    def random(cls) -> "BaseID":
        return cls(os.urandom(_ID_LEN))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * _ID_LEN)

    @classmethod
    def from_hex(cls, h: str) -> "BaseID":
        return cls(bytes.fromhex(h))

    def is_nil(self) -> bool:
        return self._bin == b"\x00" * _ID_LEN

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __hash__(self):
        return hash((self.KIND, self._bin))

    def __eq__(self, other):
        return (
            isinstance(other, BaseID)
            and other.KIND == self.KIND
            and other._bin == self._bin
        )

    def __lt__(self, other):
        return self._bin < other._bin

    def __repr__(self):
        return f"{type(self).__name__}({self._bin.hex()[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._bin,))


class JobID(BaseID):
    __slots__ = ()  # no per-instance dict (ids are hot-path objects)
    KIND = 0x01


class NodeID(BaseID):
    __slots__ = ()  # no per-instance dict (ids are hot-path objects)
    KIND = 0x02


class WorkerID(BaseID):
    __slots__ = ()  # no per-instance dict (ids are hot-path objects)
    KIND = 0x03


class ActorID(BaseID):
    __slots__ = ()  # no per-instance dict (ids are hot-path objects)
    KIND = 0x04


class TaskID(BaseID):
    __slots__ = ()  # no per-instance dict (ids are hot-path objects)
    KIND = 0x05

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        d = hashlib.blake2b(b"actor_creation:" + actor_id.binary(), digest_size=_ID_LEN)
        return cls(d.digest())


def task_return_binary(task_id: bytes, index: int) -> bytes:
    """Raw bytes of ObjectID.for_task_return without constructing either
    ID instance — the submission hot path derives return oids straight
    from the 16-byte task id it already holds."""
    return hashlib.blake2b(
        task_id + struct.pack("<I", index), digest_size=_ID_LEN
    ).digest()


class ObjectID(BaseID):
    __slots__ = ()  # no per-instance dict (ids are hot-path objects)
    KIND = 0x06

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_return_binary(task_id.binary(), index))

    @classmethod
    def for_put(cls, worker_id: WorkerID, put_index: int) -> "ObjectID":
        d = hashlib.blake2b(
            b"put:" + worker_id.binary() + struct.pack("<Q", put_index),
            digest_size=_ID_LEN,
        )
        return cls(d.digest())


class PlacementGroupID(BaseID):
    __slots__ = ()  # no per-instance dict (ids are hot-path objects)
    KIND = 0x07
