"""Model multiplexing: many models per replica, routed by model id.

Role-equivalent of ray: python/ray/serve/api.py:607 (@serve.multiplexed
+ serve.get_multiplexed_model_id): a replica lazily loads models on
first use and keeps at most ``max_num_models_per_replica`` resident
(LRU eviction); callers pick the model with
``handle.options(multiplexed_model_id=...)``.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
from collections import OrderedDict
from typing import Callable, Optional

_model_id_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "rt_multiplexed_model_id", default=""
)

#: kwarg smuggled through handle.remote() -> replica.handle_request
MODEL_ID_KWARG = "_rt_multiplexed_model_id"


def get_multiplexed_model_id() -> str:
    """The model id of the current request (empty when not multiplexed)."""
    return _model_id_ctx.get()


def set_multiplexed_model_id(model_id: str):
    _model_id_ctx.set(model_id)


def multiplexed(
    _fn: Optional[Callable] = None, *, max_num_models_per_replica: int = 3
):
    """Decorator for an async model loader ``async def get_model(self,
    model_id)``; calls are cached per replica with LRU eviction."""

    def wrap(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.multiplexed requires an async def loader")
        attr = f"__rt_mux_cache_{fn.__name__}"

        locks_attr = f"__rt_mux_locks_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(self, model_id: Optional[str] = None):
            if model_id is None:
                model_id = get_multiplexed_model_id()
            cache: OrderedDict = getattr(self, attr, None)
            if cache is None:
                cache = OrderedDict()
                setattr(self, attr, cache)
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            # per-model-id load lock: concurrent cold requests must not
            # load (and then leak) duplicate copies of the same model
            locks = getattr(self, locks_attr, None)
            if locks is None:
                locks = {}
                setattr(self, locks_attr, locks)
            lock = locks.setdefault(model_id, asyncio.Lock())
            async with lock:
                if model_id in cache:  # loaded while we waited
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = await fn(self, model_id)
                cache[model_id] = model
            locks.pop(model_id, None)
            while len(cache) > max_num_models_per_replica:
                evicted_id, evicted = cache.popitem(last=False)
                # models with a release hook get it called on eviction
                release = getattr(evicted, "__serve_multiplexed_release__",
                                  None)
                if release is not None:
                    try:
                        release()
                    except Exception:
                        pass
            return model

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
