"""ray_tpu.serve: model serving on replica actors.

Role-equivalent of ray: python/ray/serve/.  Controller reconciles
deployments to replica actors; handles route via power-of-two-choices;
an aiohttp proxy exposes HTTP.
"""

from ray_tpu.serve.api import (  # noqa: F401
    delete,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.deployment import (  # noqa: F401
    Application,
    AutoscalingConfig,
    Deployment,
    deployment,
)
from ray_tpu.serve.handle import (  # noqa: F401
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from ray_tpu.serve.asgi import ingress  # noqa: F401
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.grpc_proxy import start_grpc_proxy  # noqa: F401
from ray_tpu.serve.multiplex import (  # noqa: F401
    get_multiplexed_model_id,
    multiplexed,
)
from ray_tpu.serve.schema import (  # noqa: F401
    deploy_config,
    deploy_config_file,
)
from ray_tpu.serve.weights import (  # noqa: F401
    push_deployment_weights,
    push_weights,
)
