"""Deployment handles and the replica router.

Role-equivalent of ray: python/ray/serve/handle.py:711 (DeploymentHandle)
+ serve/_private/replica_scheduler/pow_2_scheduler.py:49.  The router
keeps a cached replica list (refreshed from the controller on a version
poll) and picks per request by power-of-two-choices over its own
in-flight counts — two random replicas, route to the lighter one.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

ROUTE_REFRESH_S = 1.0


class Router:
    def __init__(self, controller, app_name: str, deployment_name: str):
        self._controller = controller
        self._app = app_name
        self._deployment = deployment_name
        self._replicas: List[Any] = []
        self._version = -1
        self._inflight: Dict[Any, int] = {}
        self._suspect_ids: set = set()  # actor hexes on suspect nodes
        self._last_refresh = 0.0
        self._lock = threading.Lock()
        # deployment policy, learned on refresh: concurrency cap per
        # replica and the traffic plane's wire config (None = traffic
        # plane inactive, direct dispatch)
        self.max_ongoing: int = 100
        self.traffic: Optional[dict] = None
        # one RequestScheduler per deployment per process, shared by
        # every handle.options() copy (they share this Router)
        self._traffic_scheduler = None

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < ROUTE_REFRESH_S:
            return
        self._last_refresh = now
        routes = ray_tpu.get(
            self._controller.get_routes.remote(), timeout=30
        )
        entry = routes["apps"].get(self._app, {}).get(self._deployment)
        if entry is None:
            raise RuntimeError(
                f"deployment {self._deployment!r} not found in app "
                f"{self._app!r}"
            )
        with self._lock:
            self._version = routes["version"]
            self._replicas = entry["replicas"]
            self.max_ongoing = entry.get("max_ongoing", 100)
            self.traffic = entry.get("traffic")
            # health plane: replicas on failure-suspected nodes — the
            # pow-2 pick avoids them while any healthy replica exists
            # (penalty, not removal: a transient stall must not turn
            # into a failover)
            self._suspect_ids = set(entry.get("suspect") or ())
            self._inflight = {
                r: self._inflight.get(r, 0) for r in self._replicas
            }

    def pick(self):
        """Pow-2 choices over local in-flight counts."""
        self._refresh()
        deadline = time.monotonic() + 30
        while True:
            with self._lock:
                replicas = list(self._replicas)
            if replicas:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for {self._deployment!r} after 30s"
                )
            time.sleep(0.1)
            self._refresh(force=True)
        with self._lock:
            # suspect penalty: sample from healthy replicas while any
            # exist; an all-suspect deployment degrades to the plain
            # pow-2 pick (penalized capacity beats no capacity)
            if self._suspect_ids:
                healthy = [
                    r for r in replicas
                    if r._actor_id.hex() not in self._suspect_ids
                ]
                if healthy:
                    replicas = healthy
            if len(replicas) == 1:
                chosen = replicas[0]
            else:
                a, b = random.sample(replicas, 2)
                chosen = (
                    a if self._inflight.get(a, 0) <= self._inflight.get(b, 0)
                    else b
                )
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
        return chosen

    def done(self, replica):
        with self._lock:
            if replica in self._inflight:
                self._inflight[replica] = max(
                    0, self._inflight[replica] - 1
                )

    def note_dispatch(self, replica):
        """An external dispatcher (the traffic scheduler) routed a
        request to `replica`: count it in the pow-2 load signal, so
        direct-path picks see scheduler-created load AND so the
        response's _settle() done() call has a matching increment
        (without this, every scheduled completion would erase one
        DIRECT request's in-flight count)."""
        with self._lock:
            self._inflight[replica] = self._inflight.get(replica, 0) + 1

    def drop(self, replica):
        """Replica died mid-call: drop it until the next refresh."""
        with self._lock:
            self._replicas = [r for r in self._replicas if r != replica]
            self._inflight.pop(replica, None)
        self._last_refresh = 0.0
        sched = self._traffic_scheduler
        if sched is not None:
            sched.drop_replica_threadsafe(replica)


class DeploymentResponse:
    """Lazy result of a handle call (ray: serve DeploymentResponse).

    Replica death surfaces at result-fetch time (actor errors are stored
    on the ref, not raised by .remote()), so failover lives HERE: on
    ActorDiedError the router drops the replica and the request is
    re-dispatched to another one.
    """

    def __init__(
        self, router: Router, replica, ref, redispatch, attempts=3,
    ):
        self._router = router
        self._replica = replica
        self._ref = ref  # None = lazy (dispatch deferred off the io loop)
        self._redispatch = redispatch  # () -> (replica, ref)
        self._attempts = attempts
        self._done = False
        self._dispatch_lock = threading.Lock()

    def _ensure_dispatched(self):
        """Blocking first dispatch of a lazy response.  Called from the
        driver thread or an executor thread — NEVER the io loop (the
        router's route refresh blocks on a controller get, and blocking
        a replica's io loop starves the very reply it waits for).
        Locked: concurrent awaiters of one lazy response (gather, or
        await + chain) must not double-execute the request."""
        with self._dispatch_lock:
            if self._ref is None:
                self._replica, self._ref = self._redispatch()

    def result(self, timeout_s: Optional[float] = 60.0):
        from ray_tpu.core.errors import ActorDiedError, GetTimeoutError

        self._ensure_dispatched()
        while True:
            try:
                value = ray_tpu.get(self._ref, timeout=timeout_s)
            except GetTimeoutError:
                # request still occupies the replica: keep its in-flight
                # count so pow-2 doesn't pile more load onto it
                raise
            except ActorDiedError:
                # no _settle() here: drop() erases the dead replica's
                # in-flight entry wholesale, and _done must stay False
                # so the eventual settle releases the RETRY's pick —
                # settling now would leak the new replica's count
                # forever (Router._refresh preserves counts)
                self._router.drop(self._replica)
                self._attempts -= 1
                if self._attempts <= 0:
                    self._settle()
                    raise
                # under _dispatch_lock like _ensure_dispatched: a lazy
                # response can be consumed from a driver thread AND the
                # io loop at once (gather + chain), and two unlocked
                # failovers would both redispatch — the losing rebind's
                # request is orphaned and its in-flight count leaks
                with self._dispatch_lock:
                    self._replica, self._ref = self._redispatch()
                continue
            except Exception:
                self._settle()
                raise
            self._settle()
            return value

    async def result_async(self):
        """Async twin of result() with the same replica-death failover —
        awaits on the io loop instead of blocking a thread (used by the
        HTTP proxy so slow replicas can't exhaust its executor threads).
        Redispatch (which blocks on route refresh) runs in an executor.
        Loop-agnostic: on the runtime's own io loop (proxy/replica
        actors) the await is direct; any other asyncio loop (driver
        code under asyncio.run) bridges via the thread-safe future —
        the runtime's futures are bound to ITS loop and cannot be
        awaited across loops."""
        import asyncio

        from ray_tpu.core.errors import ActorDiedError
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        on_rt_loop = asyncio.get_running_loop() is rt._loop
        if self._ref is None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._ensure_dispatched
            )
        while True:
            try:
                if on_rt_loop:
                    value = await rt.await_ref(self._ref)
                else:
                    value = await asyncio.wrap_future(
                        rt.as_future(self._ref)
                    )
            except ActorDiedError:
                # mirror of result(): drop() cleans up the dead replica;
                # settling before the redispatch would strand the
                # retry's pick increment (see there)
                self._router.drop(self._replica)
                self._attempts -= 1
                if self._attempts <= 0:
                    self._settle()
                    raise
                loop = asyncio.get_running_loop()

                def _failover():
                    # rebind in the executor thread under _dispatch_lock
                    # (see result()): serializes against a concurrent
                    # sync-path failover or first dispatch
                    with self._dispatch_lock:
                        self._replica, self._ref = self._redispatch()

                await loop.run_in_executor(None, _failover)
                continue
            except Exception:
                self._settle()
                raise
            self._settle()
            return value

    def _settle(self):
        if not self._done:
            self._done = True
            self._router.done(self._replica)

    def _settle_when_resolved(self):
        """Release the upstream replica's in-flight slot only when its
        result actually lands, not at chain time — the pow-2 router's
        load signal must keep counting a still-executing request
        (chaining hands the wait to the downstream task's arg
        resolution, so nobody else will fetch this ref)."""
        if self._done:
            return
        try:
            from ray_tpu.core.runtime import get_runtime

            rt = get_runtime()

            async def waiter():
                try:
                    # completion only — fetching the value would pull a
                    # possibly-huge chained intermediate into THIS
                    # process purely for load accounting
                    await rt.await_ref_completion(self._ref)
                except Exception:
                    pass
                finally:
                    self._settle()

            rt._spawn(waiter())
        except Exception:
            self._settle()  # never leak the in-flight count

    def __await__(self):
        """`await handle.remote(...)` inside an async deployment — the
        composition idiom (reference: DeploymentResponse.__await__)."""
        return self.result_async().__await__()

    @property
    def ref(self):
        self._ensure_dispatched()
        return self._ref


class _ScheduledResponse(DeploymentResponse):
    """DeploymentResponse whose FIRST dispatch rides the traffic
    scheduler: construction enqueued the request (EDF-ordered, bounded,
    shed-on-overload); the submit future resolves to (replica, ref) at
    dispatch time or raises RequestShedError.  Failover after a replica
    death falls back to the direct dispatch closure — the retry is one
    request, not a burst, so it skips the queue."""

    def __init__(self, router: Router, submit_fut, redispatch):
        import concurrent.futures

        super().__init__(router, None, None, redispatch)
        self._submit_fut = submit_fut  # asyncio.Future on the scheduler loop
        # mirror for sync callers (result()/.ref from non-loop threads);
        # the scheduler's expiry sweep guarantees resolution by deadline
        self._mirror: "concurrent.futures.Future" = (
            concurrent.futures.Future()
        )

        def _copy(f):
            if f.cancelled():
                self._mirror.cancel()
                return
            exc = f.exception()
            if exc is not None:
                self._mirror.set_exception(exc)
            else:
                self._mirror.set_result(f.result())

        submit_fut.add_done_callback(_copy)

    def _ensure_dispatched(self):
        with self._dispatch_lock:
            if self._ref is None:
                self._replica, self._ref = self._mirror.result()

    async def result_async(self):
        if self._ref is None:
            # loop-native wait for the scheduler's dispatch: no executor
            # thread parks per queued request, so an overload backlog
            # cannot exhaust the shared pool (the admission queue holds
            # the requests; this coroutine holds ~nothing).  Caller
            # cancellation propagates to the submit future, which the
            # scheduler's flush skips and un-counts.
            replica, ref = await self._submit_fut
            with self._dispatch_lock:
                if self._ref is None:
                    self._replica, self._ref = replica, ref
        return await super().result_async()


class DeploymentResponseGenerator:
    """Streaming handle result, backed by the core streaming-generator
    transport (ObjectRefGenerator), matching ray: serve's
    DeploymentResponseGenerator.  Iteration yields VALUES; replica death
    mid-stream raises (generator state is not reconstructible on another
    replica)."""

    def __init__(self, router: Router, replica, gen, start=None):
        self._router = router
        self._replica = replica
        self._gen = gen  # None = lazy (dispatch deferred off the io loop)
        self._start = start  # () -> (replica, gen)
        self._done = False
        self._settled = False
        self._start_lock = threading.Lock()

    def _ensure_started(self):
        """Blocking first dispatch of a lazy stream (same io-loop
        starvation hazard as DeploymentResponse._ensure_dispatched)."""
        with self._start_lock:
            if self._gen is None:
                self._replica, self._gen = self._start()

    def __iter__(self):
        return self

    def __next__(self):
        self._ensure_started()
        try:
            ref = next(self._gen)
        except StopIteration:
            self._done = True
            self._settle()
            raise
        except BaseException:
            self._settle()
            raise
        try:
            return ray_tpu.get(ref)
        except BaseException:
            self._settle()
            raise

    async def _next_async(self):
        """Loop-native next + value fetch (no parked threads): used by the
        HTTP proxy's streaming path.  Raises StopAsyncIteration at end."""
        from ray_tpu.core.runtime import get_runtime

        if self._gen is None:
            import asyncio

            await asyncio.get_running_loop().run_in_executor(
                None, self._ensure_started
            )
        try:
            ref = await self._gen.__anext__()
        except StopAsyncIteration:
            self._done = True
            self._settle()
            raise
        except BaseException:
            self._settle()
            raise
        try:
            return await get_runtime().await_ref(ref)
        except BaseException:
            self._settle()
            raise

    def cancel(self):
        if not self._done and self._gen is not None:
            try:
                ray_tpu.cancel(self._gen)
            except Exception:
                pass
        self._done = True
        self._settle()

    def _settle(self):
        if not self._settled:
            self._settled = True
            self._router.done(self._replica)

    def __del__(self):
        # abandoned mid-iteration (break without cancel): free the
        # replica's stream state and ongoing-count, or the autoscaling
        # signal counts a phantom in-flight request forever
        try:
            if not self._done:
                self.cancel()
            else:
                self._settle()
        except Exception:
            pass


class DeploymentHandle:
    def __init__(
        self,
        controller,
        app_name: str,
        deployment_name: str,
        method_name: str = "__call__",
        stream: bool = False,
        multiplexed_model_id: str = "",
        slo_ms: Optional[float] = None,
    ):
        self._controller = controller
        self._app = app_name
        self._deployment = deployment_name
        self._method = method_name
        self._stream = stream
        self._model_id = multiplexed_model_id
        self._slo_ms = slo_ms  # per-handle SLO override (traffic plane)
        # proxies set this on their cached handles: args parsed from an
        # HTTP/gRPC body can never contain a DeploymentResponse, so the
        # chained-arg deep scan in remote() (O(payload)) is skipped
        self._args_known_plain = False
        self._router = Router(controller, app_name, deployment_name)

    def options(
        self,
        method_name: Optional[str] = None,
        stream: Optional[bool] = None,
        multiplexed_model_id: Optional[str] = None,
        slo_ms: Optional[float] = None,
    ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self._controller,
            self._app,
            self._deployment,
            method_name if method_name is not None else self._method,
            stream if stream is not None else self._stream,
            multiplexed_model_id
            if multiplexed_model_id is not None else self._model_id,
            slo_ms if slo_ms is not None else self._slo_ms,
        )
        h._router = self._router  # share routing state
        h._args_known_plain = self._args_known_plain
        return h

    @property
    def traffic_config(self) -> Optional[dict]:
        """The deployment's wire-form TrafficConfig, learned from the
        route table (None until the router first refreshes, and for
        deployments without a traffic plane)."""
        return self._router.traffic

    def _scheduler(self):
        """The shared per-deployment RequestScheduler bound to the
        RUNNING loop, or None when the traffic plane is inactive or the
        scheduler belongs to a different loop (fall back to direct
        dispatch rather than cross loops)."""
        import asyncio

        tc_wire = self._router.traffic
        if tc_wire is None:
            return None
        from ray_tpu.serve.traffic import RequestScheduler, TrafficConfig

        loop = asyncio.get_running_loop()
        sched = self._router._traffic_scheduler
        if sched is not None and sched._loop.is_closed():
            # the loop the scheduler was born on is gone (driver code
            # under a finished asyncio.run): rebuild on the current one
            # instead of silently disabling admission control forever —
            # anything still queued there was already dead with its loop
            sched = None
            self._router._traffic_scheduler = None
        if sched is None:
            sched = RequestScheduler(
                self._router, self._controller, self._app,
                self._deployment, TrafficConfig.from_wire(tc_wire),
            )
            sched._wire_config = tc_wire
            self._router._traffic_scheduler = sched
        elif sched._wire_config is not tc_wire:
            # the router refreshed (new wire dict object): if a redeploy
            # changed the policy, apply it to the live scheduler in
            # place (rebuilding would lose the in-flight accounting for
            # requests already dispatched).  The identity guard keeps
            # the per-request cost at one `is`; the deep compare runs
            # once per route refresh.
            if sched._wire_config != tc_wire:
                cfg = TrafficConfig.from_wire(tc_wire)
                sched.config = cfg
                sched.admission.config = cfg
            sched._wire_config = tc_wire
        return sched if sched._loop is loop else None

    @staticmethod
    def _contains_response(v) -> bool:
        """Chained-arg probe: scheduler dispatch must not have to block
        on a nested response's lazy dispatch (loop-deadlock hazard), so
        chained calls keep the direct executor-dispatched path."""
        if isinstance(v, DeploymentResponse):
            return True
        if isinstance(v, (list, tuple)):
            return any(DeploymentHandle._contains_response(x) for x in v)
        if isinstance(v, dict):
            return any(
                DeploymentHandle._contains_response(x) for x in v.values()
            )
        return False

    def remote(self, *args, **kwargs):
        import asyncio

        if self._model_id:
            from ray_tpu.serve.multiplex import MODEL_ID_KWARG

            kwargs = {**kwargs, MODEL_ID_KWARG: self._model_id}

        def materialize_chained():
            # DeploymentResponse args chain by REFERENCE: the downstream
            # replica receives the upstream result without the caller
            # materializing it (reference: passing DeploymentResponses
            # into other handle calls).  Recurses into containers, like
            # the graph-build substitution — a response nested in a list
            # would otherwise hit the serializer raw (its Router holds a
            # threading.Lock).  Runs inside dispatch — off the io loop —
            # because a lazy inner response may need its own blocking
            # first dispatch here.
            def chain(v):
                if isinstance(v, DeploymentResponse):
                    ref = v.ref  # ensures dispatched
                    v._settle_when_resolved()
                    return ref
                if isinstance(v, list):
                    return [chain(x) for x in v]
                if isinstance(v, tuple):
                    return tuple(chain(x) for x in v)
                if isinstance(v, dict):
                    return {k: chain(x) for k, x in v.items()}
                return v

            return (
                tuple(chain(a) for a in args),
                {k: chain(v) for k, v in kwargs.items()},
            )

        try:
            asyncio.get_running_loop()
            on_loop = True
        except RuntimeError:
            on_loop = False

        if self._stream:
            def start():
                a2, k2 = materialize_chained()
                replica = self._router.pick()
                try:
                    gen = replica.handle_request_stream.options(
                        num_returns="streaming"
                    ).remote(self._method, a2, k2)
                except BaseException:
                    self._router.done(replica)  # keep accounting sane
                    raise
                return replica, gen

            if on_loop:
                # a replica composing a streaming call over this handle:
                # first dispatch must not block the loop — defer it
                return DeploymentResponseGenerator(
                    self._router, None, None, start
                )
            replica, gen = start()
            return DeploymentResponseGenerator(
                self._router, replica, gen, start
            )

        def dispatch():
            a2, k2 = materialize_chained()
            replica = self._router.pick()
            ref = replica.handle_request.remote(self._method, a2, k2)
            return replica, ref

        if on_loop:
            # traffic plane: deployments with a TrafficConfig route
            # through the SLO-aware scheduler (admission + EDF + bounded
            # queue) — loop-native, non-blocking, sheds synchronously
            # with RequestShedError.  Chained-response args keep the
            # direct path (their lazy inner dispatch may block).
            if self._args_known_plain or not (
                any(map(self._contains_response, args))
                or any(map(self._contains_response, kwargs.values()))
            ):
                sched = self._scheduler()
                if sched is not None:
                    fut = sched.submit(
                        self._method, args, kwargs, self._slo_ms
                    )
                    return _ScheduledResponse(self._router, fut, dispatch)
            # inside an event loop (a replica composing over this handle,
            # or any async caller): dispatch must not block the loop —
            # defer it; result_async/await runs it on an executor thread
            return DeploymentResponse(self._router, None, None, dispatch)
        replica, ref = dispatch()
        return DeploymentResponse(self._router, replica, ref, dispatch)
