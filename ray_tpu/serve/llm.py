"""LLM serving: a continuous-batching decode replica over the Llama
KV-cache path.

Role-equivalent of ray: serve's LLM deployments (serve/llm, and the
vLLM-on-ray pattern): N concurrent streaming requests share ONE fixed
slot batch — new requests prefill into free cache rows while existing
rows keep decoding (continuous batching), every decode step is one fused
XLA call over all slots (`llama.decode_step_rowwise`, per-row
positions), and tokens stream back per request over the core
streaming-generator transport.

Wire-up::

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import LlamaDeployment

    app = LlamaDeployment.options(name="llm").bind(
        config=my_config, weights_ref=ray_tpu.put(params),
        max_slots=8, max_len=2048,
    )
    h = serve.run(app, name="llm_app")
    for tok in h.options(method_name="generate", stream=True).remote(
            prompt_ids, max_new_tokens=64):
        ...
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import time
from typing import Any, List, Optional

from ray_tpu import serve


class _Slot:
    __slots__ = ("queue", "pos", "remaining", "last_token", "max_pos")

    def __init__(self, queue, pos, remaining, last_token, max_pos):
        self.queue = queue          # per-request token queue
        self.pos = pos              # absolute position of last_token
        self.remaining = remaining  # tokens still to generate
        self.last_token = last_token
        self.max_pos = max_pos


_END = object()


class LLMEngine:
    """Slot-based continuous batcher: admit-prefill + shared decode step."""

    def __init__(self, params, config, *, max_slots: int = 4,
                 max_len: int = 256, max_prompt_len: Optional[int] = None):
        from ray_tpu.models import llama

        self._llama = llama
        self.params = params
        self.config = config
        self.max_slots = max_slots
        self.max_len = max_len
        # Sliding-window models with an explicit prompt cap get a
        # ROLLING cache: window + max_prompt - 1 slots serve ANY decode
        # length up to max_len positions (the Mistral KV-memory win;
        # llama.rolling_cache_len).  Without the cap — or without a
        # window — the cache holds every position, as before.
        self.max_prompt_len = max_prompt_len or max_len
        if config.sliding_window and max_prompt_len:
            self.cache_len = min(
                max_len, llama.rolling_cache_len(config, max_prompt_len)
            )
        else:
            self.cache_len = max_len
        self.cache = llama.init_cache(config, max_slots, self.cache_len)
        self.slots: List[Optional[_Slot]] = [None] * max_slots
        # slot admitter queue: EDF heap of
        # (deadline, seq, prompt, max_new, out_queue) — requests with a
        # traffic-plane SLO overtake deadline-less ones (deadline=inf)
        # at the free slot, and expired waiters are shed before prefill
        self._pending: List[tuple] = []
        self._admit_seq = itertools.count()
        self._runner: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        # admitter counters (bench / tests)
        self.admitted_total = 0
        self.shed_total = 0

    # -- client side -----------------------------------------------------
    async def stream(self, prompt: List[int], max_new_tokens: int = 16):
        """Async generator of generated token ids for one request.

        Captures the traffic plane's per-request deadline (when the
        request came through a TrafficConfig'd deployment) at submit
        time — the contextvar is only live in the submitting task — so
        the slot admitter can order prefill admissions EDF and shed
        requests whose SLO already lapsed in the replica's own queue.
        """
        from ray_tpu.serve.traffic.config import get_request_deadline

        if self._runner is None or self._runner.done():
            self._runner = asyncio.get_running_loop().create_task(
                self._run()
            )
        q: asyncio.Queue = asyncio.Queue()
        deadline = get_request_deadline()
        heapq.heappush(self._pending, (
            deadline if deadline is not None else float("inf"),
            next(self._admit_seq), list(prompt), int(max_new_tokens), q,
        ))
        self._wake.set()
        while True:
            tok = await q.get()
            if tok is _END:
                return
            if isinstance(tok, Exception):
                raise tok
            yield tok

    # -- engine loop -----------------------------------------------------
    async def _run(self):
        while True:
            try:
                await self._run_inner()
            except Exception as e:  # noqa: BLE001 — delivered to clients
                import logging

                logging.getLogger(__name__).exception(
                    "LLM engine step failed; failing active requests"
                )
                # fail every active stream and drain pending admissions;
                # reinitialize the cache (a donated buffer may be stale
                # after a mid-step failure) and keep serving
                for i, s in enumerate(self.slots):
                    if s is not None:
                        await s.queue.put(e)
                        await s.queue.put(_END)
                        self.slots[i] = None
                while self._pending:
                    _, _, _, _, q = heapq.heappop(self._pending)
                    await q.put(e)
                    await q.put(_END)
                self.cache = self._llama.init_cache(
                    self.config, self.max_slots, self.cache_len
                )

    async def _run_inner(self):
        import jax.numpy as jnp
        import numpy as np

        llama = self._llama
        cfg = self.config
        while True:
            # admit pending requests into free slots (prefill), EDF:
            # the earliest-deadline waiter takes the free cache row, and
            # a waiter whose deadline lapsed in this queue is shed —
            # prefill compute for a response the client already gave up
            # on would only delay every live slot's next token
            while self._pending and None in self.slots:
                deadline, _, prompt, max_new, q = heapq.heappop(
                    self._pending
                )
                if deadline <= time.monotonic():
                    from ray_tpu.serve.traffic.config import (
                        RequestShedError,
                    )

                    self.shed_total += 1
                    await q.put(RequestShedError(
                        "SLO budget exhausted before a decode slot "
                        "freed up"
                    ))
                    await q.put(_END)
                    continue
                self.admitted_total += 1
                if max_new <= 0:  # exact budget: zero tokens requested
                    await q.put(_END)
                    continue
                slot = self.slots.index(None)
                S0 = len(prompt)
                if (
                    S0 + max_new > self.max_len
                    or S0 > self.max_prompt_len
                    or S0 == 0
                ):
                    await q.put(ValueError(
                        f"prompt of {S0} tokens + {max_new} new exceeds "
                        f"max_len {self.max_len} (or prompt cap "
                        f"{self.max_prompt_len})"
                    ))
                    await q.put(_END)
                    continue
                toks = jnp.asarray([prompt], jnp.int32)

                def _prefill():
                    return llama.prefill_into_slot(
                        self.params, toks, self.cache, jnp.int32(slot),
                        cfg,
                    )

                logits, self.cache = await asyncio.to_thread(_prefill)
                first = int(jnp.argmax(logits[0]))
                await q.put(first)
                if max_new <= 1:
                    await q.put(_END)
                    continue
                self.slots[slot] = _Slot(
                    queue=q, pos=S0, remaining=max_new - 1,
                    last_token=first, max_pos=self.max_len - 1,
                )
            active = [i for i, s in enumerate(self.slots) if s is not None]
            if not active:
                # idle: park until a request arrives
                self._wake.clear()
                if not self._pending:
                    await self._wake.wait()
                continue
            # one fused decode step over ALL slots (inactive rows decode
            # into their own rows harmlessly; shape stays constant)
            tokens = np.zeros((self.max_slots,), np.int32)
            pos = np.zeros((self.max_slots,), np.int32)
            for i, s in enumerate(self.slots):
                if s is not None:
                    tokens[i] = s.last_token
                    pos[i] = s.pos

            def _step(t=tokens, p=pos):
                return llama.decode_step_rowwise(
                    self.params, jnp.asarray(t), self.cache,
                    jnp.asarray(p), cfg,
                )

            logits, self.cache = await asyncio.to_thread(_step)
            nxt = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
            for i in active:
                s = self.slots[i]
                tok = int(nxt[i])
                await s.queue.put(tok)
                s.last_token = tok
                s.pos += 1
                s.remaining -= 1
                if s.remaining <= 0 or s.pos >= s.max_pos:
                    await s.queue.put(_END)
                    self.slots[i] = None
            # let admissions/consumers run between steps
            await asyncio.sleep(0)


@serve.deployment
class LlamaDeployment:
    """Decode replica: tiny-config by default, or real weights via a
    ``weights_ref`` (object-store ref) / ``weights_loader`` callable."""

    def __init__(self, config=None, weights_ref=None, weights_loader=None,
                 max_slots: int = 4, max_len: int = 256,
                 max_prompt_len: Optional[int] = None, seed: int = 0):
        import jax

        from ray_tpu.models import llama

        self.config = config or llama.LlamaConfig.tiny()
        if weights_ref is not None:
            import ray_tpu

            params = ray_tpu.get(weights_ref)
        elif weights_loader is not None:
            params = weights_loader()
        else:
            params = llama.init(jax.random.key(seed), self.config)
        self.engine = LLMEngine(
            params, self.config, max_slots=max_slots, max_len=max_len,
            max_prompt_len=max_prompt_len,
        )

    def update_weights(self, params) -> bool:
        """Swap the decode params in place — the serve weight-push path
        (`serve.weights.push_weights` fans new weights to every replica
        via one collective broadcast, optionally block-quantized).
        In-flight decodes pick the new params up at their next step;
        the KV cache is content not weights, so it stays valid."""
        self.engine.params = params
        return True

    async def generate(self, prompt: List[int], max_new_tokens: int = 16):
        """Streaming generation (use handle.options(stream=True))."""
        async for tok in self.engine.stream(prompt, max_new_tokens):
            yield tok

    async def generate_all(self, prompt: List[int],
                           max_new_tokens: int = 16) -> List[int]:
        """Unary convenience: the full generated id list."""
        return [
            tok async for tok in self.engine.stream(prompt, max_new_tokens)
        ]
