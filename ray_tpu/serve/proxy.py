"""HTTP proxy: routes external requests to deployment replicas.

Role-equivalent of ray: python/ray/serve/_private/proxy.py:1112
(ProxyActor, HTTPProxy:748).  An aiohttp server inside an actor: request
path /<route_prefix>/... selects the app; JSON bodies become kwargs (or
the raw body is passed under "body"); responses are JSON (dict/list) or
text/bytes passthrough.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, Optional

import ray_tpu

logger = logging.getLogger(__name__)


@ray_tpu.remote
class ProxyActor:
    def __init__(self, port: int = 8000):
        self._port = port
        self._routes: Dict[str, Any] = {}  # route_prefix -> (app, deployment)
        self._handles: Dict[str, Any] = {}
        self._runner = None
        self._site = None

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, "0.0.0.0", self._port)
        await self._site.start()
        return self._port

    async def set_routes(self, routes: Dict[str, tuple]) -> bool:
        """routes: {route_prefix: (app_name, deployment_name)}"""
        self._routes = dict(routes)
        self._handles = {}
        return True

    def _handle_for(self, prefix: str):
        from ray_tpu.serve.controller import get_or_create_controller
        from ray_tpu.serve.handle import DeploymentHandle

        h = self._handles.get(prefix)
        if h is None:
            app_name, dep_name = self._routes[prefix]
            h = DeploymentHandle(
                get_or_create_controller(), app_name, dep_name
            )
            self._handles[prefix] = h
        return h

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        if path == "/-/healthz":
            return web.Response(text="ok")
        prefix = None
        for p in sorted(self._routes, key=len, reverse=True):
            if path == p or path.startswith(p.rstrip("/") + "/") or p == "/":
                prefix = p
                break
        if prefix is None:
            return web.Response(status=404, text="no route")
        kwargs: Dict[str, Any] = {}
        args = ()
        body = await request.read()
        if body:
            try:
                parsed = json.loads(body)
                if isinstance(parsed, dict):
                    kwargs = parsed
                else:
                    args = (parsed,)
            except (json.JSONDecodeError, UnicodeDecodeError):
                args = (body,)
        elif request.query:
            kwargs = dict(request.query)
        try:
            import asyncio

            logger.info("proxy: routing %s via %s", path, prefix)

            # Handle creation and handle.remote() both block (controller
            # lookup, route refresh via ray_tpu.get) — never on the io
            # loop; run them on an executor thread.
            def _route_and_dispatch():
                handle = self._handle_for(prefix)
                return handle.remote(*args, **kwargs)

            resp = await asyncio.get_running_loop().run_in_executor(
                None, _route_and_dispatch
            )
            logger.info("proxy: dispatched to replica, awaiting result")
            from ray_tpu.core.runtime import get_runtime

            rt = get_runtime()
            try:
                value = await rt.await_ref(resp._ref)
            finally:
                # success or error, the replica is done with this request
                resp._settle()
            logger.info("proxy: result ready")
        except Exception as e:  # noqa: BLE001 — surface as 500
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if isinstance(value, (dict, list)):
            return web.json_response(value)
        if isinstance(value, bytes):
            return web.Response(body=value)
        return web.Response(text=str(value))

    async def ping(self) -> bool:
        return True
