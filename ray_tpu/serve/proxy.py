"""HTTP proxy: routes external requests to deployment replicas.

Role-equivalent of ray: python/ray/serve/_private/proxy.py:1112
(ProxyActor, HTTPProxy:748).  An aiohttp server inside an actor: request
path /<route_prefix>/... selects the app; JSON bodies become kwargs (or
the raw body is passed under "body"); responses are JSON (dict/list) or
text/bytes passthrough.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.traffic.config import RequestShedError

logger = logging.getLogger(__name__)


ROUTE_POLL_S = 1.0
#: with the version-bump subscription live, a full get_routes read only
#: happens when the published version moves — plus this slow safety
#: recheck for a lost publish (GCS restart races)
ROUTE_RECHECK_S = 10.0


@ray_tpu.remote
class ProxyActor:
    def __init__(self, port: int = 8000):
        self._port = port
        self._routes: Dict[str, Any] = {}  # route_prefix -> (app, deployment)
        self._asgi_prefixes: set = set()  # prefixes served via @serve.ingress
        self._routes_version = -1
        self._last_poll = 0.0
        self._last_full_read = 0.0
        # route-table version from the controller's serve:routes pubsub
        # bumps (None until the first publish arrives); lets _poll_routes
        # skip the unbatched get_routes read while nothing changed
        self._published_version: Optional[int] = None
        self._handles: Dict[str, Any] = {}
        self._controller = None
        self._runner = None
        self._site = None
        self._start_task = None

    async def start(self) -> int:
        # memoized: concurrent callers (async actor methods interleave)
        # await ONE bring-up and all receive the resolved bound port —
        # a bare started-flag would hand an ephemeral-port caller 0
        import asyncio

        if self._start_task is None:
            self._start_task = asyncio.get_running_loop().create_task(
                self._do_start()
            )
        return await asyncio.shield(self._start_task)

    async def _do_start(self) -> int:
        from aiohttp import web

        # route-table refresh rides the GCS pubsub plane: the controller
        # publishes version bumps (coalesced into the per-tick BATCH
        # frames like every GCS notify), so the per-request poll below
        # degrades to a no-op while the table is unchanged instead of an
        # unbatched get_routes read per second
        try:
            from ray_tpu.core.runtime import get_runtime
            from ray_tpu.serve.controller import ROUTES_CHANNEL

            def _on_bump(msg: dict) -> None:
                self._published_version = msg.get("version")

            await get_runtime().subscribe_async(ROUTES_CHANNEL, _on_bump)
        except Exception:
            logger.debug("routes subscription failed; falling back to "
                         "polling", exc_info=True)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, "0.0.0.0", self._port)
        await self._site.start()
        if self._port == 0:  # ephemeral: resolve the real port
            for server in self._runner.sites:
                self._port = server._server.sockets[0].getsockname()[1]
                break
        return self._port

    # Route state is owned by the controller (like the reference's
    # long-poll config push, serve/_private/long_poll.py); the proxy polls
    # the versioned get_routes instead of accepting driver-pushed
    # snapshots — so concurrent drivers can't clobber each other's routes.
    def _poll_routes(self, force: bool = False):
        import time

        now = time.monotonic()
        if not force and now - self._last_poll < ROUTE_POLL_S:
            return
        self._last_poll = now
        if (
            not force
            and self._published_version is not None
            and self._published_version == self._routes_version
            and now - self._last_full_read < ROUTE_RECHECK_S
        ):
            # subscription says nothing moved: skip the read entirely
            return
        self._last_full_read = now
        if self._controller is None:
            from ray_tpu.serve.controller import get_or_create_controller

            self._controller = get_or_create_controller()
        routes = ray_tpu.get(self._controller.get_routes.remote(), timeout=30)
        if routes["version"] != self._routes_version:
            self._routes_version = routes["version"]
            new_routes = routes.get("http_routes", {})
            # drop handles for prefixes that changed target
            for p, target in list(self._handles.items()):
                if new_routes.get(p) != self._routes.get(p):
                    self._handles.pop(p, None)
            self._routes = dict(new_routes)
            self._asgi_prefixes = set(routes.get("asgi_prefixes", ()))

    def _handle_for(self, prefix: str):
        from ray_tpu.serve.handle import DeploymentHandle

        h = self._handles.get(prefix)
        if h is None:
            app_name, dep_name = self._routes[prefix]
            h = DeploymentHandle(self._controller, app_name, dep_name)
            # args come from a parsed HTTP body — they can never hold a
            # DeploymentResponse, so remote() skips the chained-arg scan
            h._args_known_plain = True
            self._handles[prefix] = h
        return h

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        if path == "/-/healthz":
            return web.Response(text="ok")
        kwargs: Dict[str, Any] = {}
        args = ()
        # routing modifiers ride the query string (never the body):
        #   ?method=generate   call a named method instead of __call__
        #   ?stream=1          newline-delimited-JSON streaming response
        method_name = request.query.get("method")
        want_stream = request.query.get("stream", "") in ("1", "true")
        if method_name and method_name.startswith("_"):
            # the replica getattr()s the user callable: private/dunder
            # attributes must not be reachable over unauthenticated HTTP
            return web.Response(
                status=403, text="private method names are not routable"
            )
        body = await request.read()
        if body:
            try:
                parsed = json.loads(body)
                if isinstance(parsed, dict):
                    kwargs = parsed
                else:
                    args = (parsed,)
            except (json.JSONDecodeError, UnicodeDecodeError):
                args = (body,)
        elif request.query:
            kwargs = {
                k: v for k, v in request.query.items()
                if k not in ("method", "stream")
            }
        try:
            import asyncio

            def _match():
                for p in sorted(self._routes, key=len, reverse=True):
                    if (
                        path == p
                        or path.startswith(p.rstrip("/") + "/")
                        or p == "/"
                    ):
                        return p
                return None

            # Routing + dispatch block (controller poll, route refresh) —
            # run them on an executor thread.  The (possibly long) replica
            # wait is awaited on the io loop with failover, so slow
            # replicas can't exhaust the executor pool.
            def _route_and_dispatch():
                self._poll_routes()
                prefix = _match()
                if prefix is None:
                    # one forced refresh: the route may have just been added
                    self._poll_routes(force=True)
                    prefix = _match()
                if prefix is None:
                    return None, False
                handle = self._handle_for(prefix)
                if prefix in self._asgi_prefixes:
                    # @serve.ingress deployment: forward the raw request
                    # through the replica's ASGI adapter with the prefix
                    # stripped, so the mounted app's own routing applies
                    # (ray: serve/api.py:172 ingress semantics)
                    suffix = path[len(prefix.rstrip("/")):] or "/"
                    asgi_req = {
                        "method": request.method,
                        "path": suffix,
                        "query_string": request.query_string,
                        "headers": [
                            (k, v) for k, v in request.headers.items()
                        ],
                        "body": body,
                    }
                    h = handle.options(method_name="__asgi_handle__")
                    return h.remote(asgi_req), True
                if method_name or want_stream:
                    handle = handle.options(
                        method_name=method_name or "__call__",
                        stream=want_stream,
                    )
                # traffic-plane deployments dispatch ON the io loop (the
                # scheduler is loop-bound and admission sheds
                # synchronously); learn the policy here, where blocking
                # on a route refresh is allowed.  Streams and plain
                # deployments keep the direct executor dispatch.
                if not want_stream:
                    r = handle._router
                    if r._version < 0:
                        try:
                            r._refresh(force=True)
                        except Exception:
                            pass  # dispatch will surface routing errors
                    if handle.traffic_config is not None:
                        return ("traffic", handle), False
                return handle.remote(*args, **kwargs), False

            resp, is_asgi = await asyncio.get_running_loop().run_in_executor(
                None, _route_and_dispatch
            )
            if resp is None:
                return web.Response(status=404, text="no route")
            if isinstance(resp, tuple) and resp[0] == "traffic":
                # on-loop dispatch: admission check + EDF enqueue (pure
                # arithmetic + a heap push — nothing here blocks)
                resp = resp[1].remote(*args, **kwargs)
            if is_asgi:
                r = await resp.result_async()
                headers = {
                    k: v for k, v in r.get("headers", [])
                    # aiohttp computes these from the body it writes
                    if k.lower() not in ("content-length",
                                         "transfer-encoding")
                }
                return web.Response(
                    status=r.get("status", 500),
                    headers=headers,
                    body=r.get("body", b""),
                )
            if want_stream:
                # newline-delimited JSON over chunked transfer (the HTTP
                # face of the core streaming-generator transport), fully
                # loop-native: no executor thread parks per stream, so
                # slow token cadences can't exhaust the shared pool.
                # Errors after prepare() must TERMINATE this stream (an
                # error line + eof) — the outer handler's 500 would write
                # a second response into the open chunked body.
                sr = web.StreamResponse(
                    headers={"Content-Type": "application/x-ndjson"}
                )
                await sr.prepare(request)
                try:
                    while True:
                        try:
                            item = await resp._next_async()
                        except StopAsyncIteration:
                            break
                        await sr.write(
                            (json.dumps(item, default=str) + "\n").encode()
                        )
                except Exception as e:  # noqa: BLE001 — ends the stream
                    try:
                        await sr.write((json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ) + "\n").encode())
                    except Exception:
                        pass  # client already gone
                try:
                    await sr.write_eof()
                except Exception:
                    pass
                return sr
            # result_async carries the pow-2 router's replica-death
            # failover — HTTP clients get the same retry semantics as
            # handle-API callers instead of a bare 500.
            value = await resp.result_async()
        except RequestShedError as e:
            # load shed: fast-fail with the standard overload answer so
            # clients back off instead of retry-storming (Retry-After is
            # whole seconds per RFC 9110)
            import math

            return web.Response(
                status=503,
                headers={
                    "Retry-After": str(max(1, math.ceil(e.retry_after_s)))
                },
                text=str(e),
            )
        except Exception as e:  # noqa: BLE001 — surface as 500
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if isinstance(value, (dict, list)):
            return web.json_response(value)
        if isinstance(value, bytes):
            return web.Response(body=value)
        return web.Response(text=str(value))

    async def ping(self) -> bool:
        return True
