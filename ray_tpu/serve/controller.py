"""Serve controller: reconciles deployment specs to replica actors.

Role-equivalent of ray: python/ray/serve/_private/controller.py:86
(ServeController) + deployment_state.py (DeploymentStateManager:2307) +
autoscaling_state.py (get_decision_num_replicas:261).  A detached named
actor: holds app → deployment → replica state; a background reconcile
THREAD creates/kills replicas to match targets, replaces dead ones, and
computes autoscaling decisions from replica ongoing-request counts.
(A thread, not an asyncio task: actor creation and ray_tpu.get are
blocking calls, which must never run on the worker's io loop.)
Handles/proxies poll `get_routes` (versioned) instead of the reference's
long-poll channel — same effect, simpler transport.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List

import ray_tpu
from ray_tpu.serve.replica import ReplicaActor

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"

#: GCS pubsub channel for route-table version bumps: proxies subscribe
#: and fetch the full table only when the version moves, instead of an
#: unbatched get_routes read per 1 s poll (the bump notify rides the
#: same batched rpc plane as every other GCS push)
ROUTES_CHANNEL = "serve:routes"


class _DeploymentState:
    def __init__(self, app_name: str, deployment):
        self.app_name = app_name
        self.deployment = deployment
        self.replicas: List[Any] = []  # ActorHandles
        # scale-down victims finishing in-flight work: (handle, stop
        # deadline).  Excluded from get_routes, so routers stop picking
        # them; killed once idle or past the drain timeout.
        self.draining: List[tuple] = []
        # queue-depth reports from traffic-plane schedulers:
        # reporter id -> (monotonic timestamp, snapshot dict)
        self.traffic_reports: Dict[Any, tuple] = {}
        self.target = (
            deployment.autoscaling_config.min_replicas
            if deployment.autoscaling_config
            else deployment.num_replicas
        )
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0

    @property
    def name(self) -> str:
        return self.deployment.name

    def traffic_wire(self):
        tc = getattr(self.deployment, "traffic_config", None)
        if tc is None:
            return None
        return tc.to_wire() if hasattr(tc, "to_wire") else dict(tc)

    def drain_timeout_s(self) -> float:
        tc = getattr(self.deployment, "traffic_config", None)
        return getattr(tc, "drain_timeout_s", 30.0) if tc else 30.0


@ray_tpu.remote
class ServeControllerActor:
    def __init__(self):
        self._apps: Dict[str, Dict[str, _DeploymentState]] = {}
        # HTTP route table lives HERE, not in any driver process (the
        # reference keeps route state in the controller too:
        # serve/_private/controller.py) — so a second driver or a driver
        # restart can't clobber routes installed by others.
        self._http_routes: Dict[str, tuple] = {}  # prefix -> (app, deployment)
        # prefixes whose target class carries @serve.ingress (ASGI): the
        # HTTP proxy dispatches those through the ASGI adapter instead of
        # the method-call convention
        self._asgi_prefixes: set = set()
        self._app_roots: Dict[str, str] = {}  # app -> ingress deployment
        self._routes_version = 0
        self._lock = threading.RLock()
        # serializes whole reconcile passes (the loop thread and
        # deploy_application both call _reconcile_once; interleaved passes
        # would double-create replicas)
        self._reconcile_mutex = threading.Lock()
        self._interval = 0.5
        self._stop = threading.Event()
        # nodes with a graceful drain in flight (GCS "nodes" pubsub):
        # replicas on them enter the drain-then-stop flow — replaced and
        # routed around BEFORE the node dies — instead of dying with it
        self._draining_nodes: set = set()
        # failure-SUSPECTED nodes (health plane): replicas there are NOT
        # killed or replaced — they are only penalized in the routers'
        # pow-2 pick via the route table's per-deployment suspect set,
        # so a transient stall costs routing preference, not a failover
        self._suspect_nodes: set = set()
        self._suspect_replicas: Dict[tuple, tuple] = {}  # (app, dep) -> ids
        try:
            from ray_tpu.core.runtime import get_runtime

            get_runtime().subscribe("nodes", self._on_node_event)
            # seed with drains/suspicions already in flight: their events
            # were published before this controller subscribed (controller
            # restart / serve.start during a preemption window)
            for n in get_runtime().nodes():
                if n.get("draining"):
                    self._draining_nodes.add(n["node_id"])
                if n.get("suspect"):
                    self._suspect_nodes.add(n["node_id"])
        except Exception:
            logger.warning("node-event subscribe failed", exc_info=True)
        self._thread = threading.Thread(
            target=self._reconcile_loop, name="serve-reconcile", daemon=True
        )
        self._thread.start()

    def _on_node_event(self, msg: dict):
        """GCS pubsub callback (io loop): track draining and
        failure-suspected nodes."""
        nid = msg.get("node_id")
        if nid is None:
            return
        event = msg.get("event")
        if event == "draining":
            self._draining_nodes.add(nid)
        elif event in ("dead", "alive"):
            self._draining_nodes.discard(nid)
            self._suspect_nodes.discard(nid)
        if event == "suspect":
            self._suspect_nodes.add(nid)
        elif event == "recovered":
            self._suspect_nodes.discard(nid)

    # -- deploy API ------------------------------------------------------
    def deploy_application(
        self, app_name: str, deployments: list, root_name: str = None
    ) -> bool:
        """Deploy/update an app (list of Deployment objects).

        ``root_name`` marks the ingress deployment of a composed graph
        (children are listed before parents, so "first in list" is NOT
        the ingress); defaults to the first deployment for single-node
        apps and config-file deploys."""
        with self._lock:
            self._app_roots[app_name] = (
                root_name if root_name is not None
                else (deployments[0].name if deployments else None)
            )
            states = self._apps.setdefault(app_name, {})
            new_names = {d.name for d in deployments}
            for name in list(states):
                if name not in new_names:
                    self._drain(states.pop(name))
            for d in deployments:
                existing = states.get(d.name)
                if existing is not None:
                    # redeploy: replace spec, restart replicas
                    self._drain(existing)
                states[d.name] = _DeploymentState(app_name, d)
            self._routes_version += 1
        self._reconcile_once()
        self._publish_routes_version()
        return True

    def get_app_root(self, app_name: str):
        with self._lock:
            return self._app_roots.get(app_name)

    def get_replica_actors(self, name: str, app_name: str = "default"):
        """Live replica actor handles for one deployment (draining
        victims excluded) — the target set for a collective weight
        push (serve.weights.push_deployment_weights)."""
        with self._lock:
            states = self._apps.get(app_name, {})
            st = states.get(name)
            if st is None:
                raise KeyError(
                    f"no deployment {name!r} in app {app_name!r} "
                    f"(known: {sorted(states)})"
                )
            return list(st.replicas)

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            self._app_roots.pop(app_name, None)
            states = self._apps.pop(app_name, {})
            for st in states.values():
                self._drain(st)
            for prefix, (app, _d) in list(self._http_routes.items()):
                if app == app_name:
                    del self._http_routes[prefix]
                    self._asgi_prefixes.discard(prefix)
            self._routes_version += 1
        self._publish_routes_version()
        return True

    def set_route_prefix(
        self, prefix: str, app_name: str, deployment_name: str
    ) -> bool:
        with self._lock:
            self._http_routes[prefix] = (app_name, deployment_name)
            st = self._apps.get(app_name, {}).get(deployment_name)
            if st is not None and getattr(
                st.deployment.func_or_class, "__rt_is_asgi__", False
            ):
                self._asgi_prefixes.add(prefix)
            else:
                self._asgi_prefixes.discard(prefix)
            self._routes_version += 1
        self._publish_routes_version()
        return True

    def remove_route_prefix(self, prefix: str) -> bool:
        with self._lock:
            removed = self._http_routes.pop(prefix, None) is not None
            self._asgi_prefixes.discard(prefix)
            if removed:
                self._routes_version += 1
        if removed:
            self._publish_routes_version()
        return removed

    def _publish_routes_version(self):
        """Push the current route version on the GCS pubsub plane so
        proxies refresh on change instead of polling with a full
        get_routes read every second (the push itself coalesces into
        the per-tick BATCH frames like any other GCS notify)."""
        from ray_tpu.core.runtime import get_runtime

        with self._lock:
            v = self._routes_version
        try:
            get_runtime().publish(ROUTES_CHANNEL, {"version": v})
        except Exception:
            logger.debug("routes version publish failed", exc_info=True)

    def _drain(self, st: _DeploymentState):
        for r in st.replicas:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        st.replicas = []
        # app deleted / redeployed: draining replicas lose their grace
        for r, _deadline in st.draining:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        st.draining = []

    # -- reconcile -------------------------------------------------------
    def _reconcile_loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._reconcile_once()
                self._autoscale()
            except Exception:
                logger.exception("reconcile failed")

    def _snapshot(self) -> List[_DeploymentState]:
        with self._lock:
            return [
                st
                for states in self._apps.values()
                for st in states.values()
            ]

    def _is_current(self, st: _DeploymentState) -> bool:
        with self._lock:
            return self._apps.get(st.app_name, {}).get(st.name) is st

    def _check_health(self, replicas: List[Any]) -> List[Any]:
        """Batched health probe: errored replicas are dead; replicas that
        simply haven't answered within the window get the benefit of the
        doubt (busy, not dead) — one hung replica must not stall
        reconciliation for everyone (single reconcile thread)."""
        if not replicas:
            return []
        refs = [r.check_health.remote() for r in replicas]
        ready, _pending = ray_tpu.wait(
            refs, num_returns=len(refs), timeout=10.0, fetch_local=True
        )
        ready_set = set(ready)
        alive = []
        for r, ref in zip(replicas, refs):
            if ref not in ready_set:
                alive.append(r)  # slow, assumed busy
                continue
            try:
                ray_tpu.get(ref, timeout=1)
                alive.append(r)
            except Exception:
                pass  # dead
        return alive

    def _reconcile_once(self):
        with self._reconcile_mutex:
            changed = self._reconcile_locked()
        if changed:
            self._publish_routes_version()

    def _actor_nodes(self) -> Dict[str, str]:
        """actor_id hex -> node_id hex for every live actor (one GCS
        read per reconcile pass, and only while a node is draining)."""
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        rows = rt._run(rt.gcs.call("list_actors", {}))
        return {
            r["actor_id"]: r["node_id"]
            for r in rows
            if r.get("node_id") and r["state"] == "ALIVE"
        }

    def _reconcile_locked(self) -> bool:
        changed = False
        draining_nodes = set(self._draining_nodes)
        suspect_nodes = set(self._suspect_nodes)
        actor_nodes: Dict[str, str] = (
            self._actor_nodes()
            if (draining_nodes or suspect_nodes
                or self._suspect_replicas) else {}
        )
        for st in self._snapshot():
            alive = self._check_health(st.replicas)
            if draining_nodes:
                # replicas on a draining node: drain-then-stop NOW — they
                # leave the route table (and get replaced below via
                # to_create) while the node is still alive to finish
                # their in-flight requests, instead of dying with it
                evacuating = [
                    r for r in alive
                    if actor_nodes.get(r._actor_id.hex()) in draining_nodes
                ]
                if evacuating:
                    alive = [r for r in alive if r not in evacuating]
                    with self._lock:
                        if self._is_current(st):
                            deadline = (
                                time.monotonic() + st.drain_timeout_s()
                            )
                            for r in evacuating:
                                st.draining.append((r, deadline))
                            logger.info(
                                "deployment %s: %d replica(s) on draining "
                                "node(s) entered drain-then-stop",
                                st.name, len(evacuating),
                            )
            with self._lock:
                if not self._is_current(st):
                    continue  # redeployed/deleted while we probed
                if st.replicas != alive:
                    st.replicas = alive
                    changed = True
                d = st.deployment
                to_create = st.target - len(st.replicas)
                to_remove = len(st.replicas) - st.target
            for _ in range(max(0, to_create)):
                opts = dict(d.ray_actor_options)
                handle = ReplicaActor.options(
                    num_cpus=opts.get("num_cpus", 0.1),
                    num_tpus=opts.get("num_tpus"),
                    resources=opts.get("resources"),
                    max_restarts=0,
                    # this controller owns replica relocation (the
                    # drain-then-stop flow above); the GCS drain plane
                    # must not also checkpoint/restart-migrate them
                    on_drain="ignore",
                ).remote(
                    d.func_or_class, d.init_args, d.init_kwargs, None,
                    st.app_name,
                )
                with self._lock:
                    if self._is_current(st):
                        st.replicas.append(handle)
                        changed = True
                        handle = None
                if handle is not None:
                    # state was drained while we created: don't leak
                    try:
                        ray_tpu.kill(handle)
                    except Exception:
                        pass
            for _ in range(max(0, to_remove)):
                # drain-then-stop: the victim leaves the route table NOW
                # (routers stop picking it on their next refresh) but
                # keeps running until its in-flight requests finish —
                # scale-down must never turn admitted requests into
                # replica-death errors
                with self._lock:
                    victim = (
                        st.replicas.pop()
                        if self._is_current(st) and st.replicas
                        else None
                    )
                    if victim is not None:
                        st.draining.append((
                            victim,
                            time.monotonic() + st.drain_timeout_s(),
                        ))
                if victim is not None:
                    changed = True
            # health plane: replicas hosted on failure-SUSPECTED nodes
            # stay in the route table (nothing is failed over for a
            # suspicion) but are marked so routers penalize them in the
            # pow-2 pick; set changes bump the routes version
            key = (st.app_name, st.name)
            suspect_ids = tuple(sorted(
                r._actor_id.hex()
                for r in st.replicas
                if actor_nodes.get(r._actor_id.hex()) in suspect_nodes
            )) if (suspect_nodes or self._suspect_replicas.get(key)) else ()
            with self._lock:
                if self._is_current(st) and (
                    self._suspect_replicas.get(key, ()) != suspect_ids
                ):
                    if suspect_ids:
                        self._suspect_replicas[key] = suspect_ids
                    else:
                        self._suspect_replicas.pop(key, None)
                    changed = True
            if st.draining:
                # NOT folded into `changed`: a drained victim already
                # left the route table when draining began, so killing
                # it must not bump the version and fan a fleet-wide
                # get_routes re-read out to every proxy
                self._sweep_draining(st)
        if changed:
            with self._lock:
                self._routes_version += 1
        return changed

    def _sweep_draining(self, st: _DeploymentState) -> None:
        """Stop draining replicas that are idle (queue_len 0), dead, or
        past their drain deadline.  Probes are batched like
        _check_health — one busy draining replica must not stall the
        single reconcile thread for everyone; a replica that doesn't
        answer within the window just stays draining until the next
        sweep (or its deadline)."""
        with self._lock:
            draining = list(st.draining)
        if not draining:
            return
        now = time.monotonic()
        refs = [r.queue_len.remote() for r, _ in draining]
        ready, _pending = ray_tpu.wait(
            refs, num_returns=len(refs), timeout=5.0, fetch_local=True
        )
        ready_set = set(ready)
        stopped = []
        for (replica, deadline), ref in zip(draining, refs):
            stop = now >= deadline
            if not stop and ref in ready_set:
                try:
                    stop = ray_tpu.get(ref, timeout=1) == 0
                except Exception:
                    stop = True  # dead already
            if stop:
                try:
                    ray_tpu.kill(replica)
                except Exception:
                    pass
                stopped.append(replica)
        if not stopped:
            return
        with self._lock:
            st.draining = [
                (r, d) for r, d in st.draining if r not in stopped
            ]

    def _queued_depth(self, st: _DeploymentState, now: float) -> float:
        """Sum of queued (admitted, undispatched) requests across the
        traffic-plane schedulers that reported recently.  Stale
        reporters (a proxy that died or went idle) age out so a
        vanished queue cannot pin the deployment scaled up."""
        tc = getattr(st.deployment, "traffic_config", None)
        horizon = 3.0 * getattr(tc, "stats_push_interval_s", 0.5) + 2.0
        total = 0.0
        with self._lock:
            for reporter, (t, snap) in list(st.traffic_reports.items()):
                if now - t > horizon:
                    del st.traffic_reports[reporter]
                    continue
                total += float(snap.get("queued", 0))
        return total

    def _autoscale(self):
        now = time.monotonic()
        for st in self._snapshot():
            asc = st.deployment.autoscaling_config
            if asc is None or not st.replicas:
                continue
            try:
                lens = ray_tpu.get(
                    [r.queue_len.remote() for r in st.replicas], timeout=30
                )
            except Exception:
                continue
            # autoscaling signal = replica-ongoing PLUS scheduler queue
            # depth: under admission control replicas never see more
            # than max_ongoing at once, so the queue — where overload
            # actually accumulates — must drive the scale-up
            total = float(sum(lens)) + self._queued_depth(st, now)
            desired = max(
                asc.min_replicas,
                min(
                    asc.max_replicas,
                    int(-(-total // asc.target_ongoing_requests)),
                ),
            )
            with self._lock:
                if desired > st.target:
                    if now - st.last_scale_up >= asc.upscale_delay_s:
                        st.target = desired
                        st.last_scale_up = now
                elif desired < st.target:
                    if now - st.last_scale_down >= asc.downscale_delay_s:
                        st.target = max(desired, asc.min_replicas)
                        st.last_scale_down = now
                else:
                    st.last_scale_up = now
                    st.last_scale_down = now

    # -- traffic-plane stats ingest --------------------------------------
    def report_traffic_stats(
        self, app_name: str, deployment_name: str, reporter, snapshot: dict
    ) -> bool:
        """Fire-and-forget depth/rate push from a RequestScheduler
        (one per routing process).  Reports are keyed by reporter so
        several proxies sum, not clobber."""
        with self._lock:
            st = self._apps.get(app_name, {}).get(deployment_name)
            if st is None:
                return False
            st.traffic_reports[reporter] = (time.monotonic(), dict(snapshot))
        return True

    # -- discovery (handles / proxies poll this) -------------------------
    def get_routes(self) -> dict:
        with self._lock:
            out = {}
            for app_name, states in self._apps.items():
                out[app_name] = {
                    name: {
                        "replicas": list(st.replicas),
                        "max_ongoing": st.deployment.max_ongoing_requests,
                        "traffic": st.traffic_wire(),
                        # replica actor-id hexes on failure-suspected
                        # nodes: routers penalize, never drop
                        "suspect": list(
                            self._suspect_replicas.get((app_name, name), ())
                        ),
                    }
                    for name, st in states.items()
                }
            return {
                "version": self._routes_version,
                "apps": out,
                "http_routes": dict(self._http_routes),
                "asgi_prefixes": list(self._asgi_prefixes),
            }

    def get_status(self) -> dict:
        with self._lock:
            return {
                app_name: {
                    name: {
                        "target_replicas": st.target,
                        "running_replicas": len(st.replicas),
                        "draining_replicas": len(st.draining),
                    }
                    for name, st in states.items()
                }
                for app_name, states in self._apps.items()
            }

    def ping(self) -> bool:
        return True


def get_or_create_controller():
    """The controller is a detached named actor, one per cluster."""
    handle = ServeControllerActor.options(
        name=CONTROLLER_NAME,
        get_if_exists=True,
        lifetime="detached",
        num_cpus=0.1,
    ).remote()
    return handle
