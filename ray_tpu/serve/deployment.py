"""Deployment definitions.

Role-equivalent of ray: python/ray/serve/deployment.py:87 (Deployment) and
the @serve.deployment decorator (serve/api.py:248).  A deployment is a
replicated callable with scaling policy; `.bind(*args)` produces an
Application ready for serve.run.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    """(ray: serve/config.py AutoscalingConfig)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 10.0


@dataclasses.dataclass
class Deployment:
    func_or_class: Any
    name: str
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    num_replicas: int = 1
    autoscaling_config: Optional[AutoscalingConfig] = None
    max_ongoing_requests: int = 100
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    user_config: Any = None
    # SLO/queueing policy (serve/traffic/config.py TrafficConfig); None
    # keeps the direct pow-2 dispatch path with no admission control
    traffic_config: Any = None

    def __post_init__(self):
        # normalize HERE, not only in the decorator: .options(
        # autoscaling_config={...}) / .options(traffic_config={...})
        # go through dataclasses.replace (the declarative schema's
        # override path too), and a raw dict would crash the
        # controller's `.min_replicas` access resp. make its
        # attribute-based traffic accessors (drain_timeout_s,
        # stats_push_interval_s) silently fall back to defaults.
        # Strict kwargs so a typo'd key raises at definition time.
        if isinstance(self.autoscaling_config, dict):
            self.autoscaling_config = AutoscalingConfig(
                **self.autoscaling_config
            )
        if isinstance(self.traffic_config, dict):
            from ray_tpu.serve.traffic.config import TrafficConfig

            self.traffic_config = TrafficConfig(**self.traffic_config)

    def options(self, **kwargs) -> "Deployment":
        return dataclasses.replace(self, **kwargs)

    def bind(self, *args, **kwargs) -> "Application":
        """Bind init args, producing an Application node.

        Args may themselves be Application objects (other bound
        deployments): that composes a multi-deployment app graph — at
        serve.run each nested Application becomes its own deployment and
        the parent receives a live DeploymentHandle in its place
        (reference: serve/_private/deployment_graph_build.py:65-69).
        """
        return Application(
            dataclasses.replace(self, init_args=args, init_kwargs=kwargs)
        )

    def __call__(self, *a, **k):
        raise RuntimeError(
            "deployments are not called directly; use serve.run + a handle"
        )


@dataclasses.dataclass
class Application:
    """One node of a deployment graph.  ``deployment.init_args`` /
    ``init_kwargs`` may contain further Application nodes; binding the
    SAME Application object into several parents shares one deployment
    (and its replicas), exactly like the reference's DAG build."""

    deployment: Deployment


@dataclasses.dataclass(frozen=True)
class HandleRef:
    """Placeholder left in a deployment's init args where a nested
    Application was bound; the replica resolves it to a DeploymentHandle
    for the named deployment in the same app at construction time."""

    deployment_name: str


def deployment(
    _func_or_class: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Optional[int] = None,
    autoscaling_config: Optional[dict] = None,
    max_ongoing_requests: int = 100,
    ray_actor_options: Optional[dict] = None,
    traffic_config: Optional[dict] = None,
):
    """@serve.deployment decorator (ray: serve/api.py:248)."""

    def wrap(target) -> Deployment:
        asc = autoscaling_config
        if isinstance(asc, dict):
            asc = AutoscalingConfig(**asc)
        # traffic_config dicts normalize in Deployment.__post_init__
        tc = traffic_config
        return Deployment(
            func_or_class=target,
            name=name or target.__name__,
            num_replicas=num_replicas or 1,
            autoscaling_config=asc,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options or {},
            traffic_config=tc,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
