"""Collective weight push for serve deployments (Collectives v2).

Live weight updates for replicated deployments: the driver ships the
new weights ONCE (to rank 0, as a task argument over the object
plane), a transient collective group fans them out replica-to-replica
(ring/btree over RPC + shm — no N-fold driver upload), and every
replica applies them through its ``update_weights`` method.  With
``wire_dtype="bf16"|"int8"`` the float32 leaves ride the
block-quantized tensor path — every replica (rank 0 included) adopts
the decode of the single encoding, so the fleet stays bit-identical,
which is exactly the invariant replicated serving needs (two replicas
answering the same prompt differently is a correctness bug; a bounded
quantization delta vs the trainer's copy is a quality knob).

Quick shape::

    from ray_tpu.serve import weights as sw

    # by deployment name (replica handles fetched from the controller):
    sw.push_deployment_weights("llm", new_params, wire_dtype="bf16")

    # or directly over actor handles (any actors with the method):
    sw.push_weights(actors, new_params, wire_dtype="int8")
"""

from __future__ import annotations

import uuid
from typing import Any, List, Optional

__all__ = ["push_weights", "push_deployment_weights"]


def _push_in_actor(inst, group: str, world: int, rank: int, weights,
                   wire_dtype, method: str):
    """Runs inside each target actor (executor thread via ``_apply``).
    Serve's ReplicaActor wraps the user object at ``_callable``; plain
    actors ARE the target."""
    from ray_tpu.util import collective as col

    target = getattr(inst, "_callable", inst)
    apply_fn = getattr(target, method)
    if world == 1:
        apply_fn(weights)
        return True
    col.init_collective_group(world, rank, group_name=group)
    try:
        w = col.broadcast_tree(
            weights, src_rank=0, group_name=group, wire_dtype=wire_dtype
        )
        apply_fn(w)
        # the broadcast root finishes as soon as its sends are acked
        # (receivers buffer chunks in mailboxes even pre-init), so
        # WITHOUT this barrier a fast rank 0 would destroy the group —
        # retracting its rendezvous key — before slow ranks' membership
        # polls ever saw it, wedging their init until timeout
        col.barrier(group_name=group)
    finally:
        col.destroy_collective_group(group_name=group)
    return True


def push_weights(actors: List[Any], weights, *,
                 wire_dtype: Optional[str] = None,
                 method: str = "update_weights",
                 group_name: Optional[str] = None,
                 timeout: Optional[float] = None) -> int:
    """Push ``weights`` (a pytree of numpy arrays) to every actor in
    ``actors`` via one collective broadcast; each actor applies them
    with ``method``.  Returns the number of actors updated.

    The driver uploads the payload once (rank 0's task argument); the
    group moves it between replicas over the RPC + shm plane, and the
    transient group is always destroyed — a failed push never leaks a
    group name."""
    import ray_tpu
    from ray_tpu.common.config import cfg

    if not actors:
        return 0
    group = group_name or f"weight-push-{uuid.uuid4().hex[:8]}"
    world = len(actors)
    refs = [
        a._apply(
            _push_in_actor, group, world, rank,
            weights if rank == 0 else None, wire_dtype, method,
        )
        for rank, a in enumerate(actors)
    ]
    ray_tpu.get(
        refs,
        timeout=timeout if timeout is not None
        else cfg.collective_rendezvous_timeout_s + 60.0,
    )
    return world


def push_deployment_weights(name: str, weights, *,
                            app_name: str = "default",
                            wire_dtype: Optional[str] = None,
                            method: str = "update_weights",
                            timeout: Optional[float] = None) -> int:
    """``push_weights`` over the live replicas of one serve deployment
    (handles fetched from the controller; draining victims excluded)."""
    import ray_tpu
    from ray_tpu.serve.controller import get_or_create_controller

    controller = get_or_create_controller()
    actors = ray_tpu.get(
        controller.get_replica_actors.remote(name, app_name), timeout=30.0
    )
    return push_weights(
        actors, weights, wire_dtype=wire_dtype, method=method,
        timeout=timeout,
    )
