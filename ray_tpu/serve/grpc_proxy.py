"""gRPC ingress for Serve.

Role-equivalent of ray: python/ray/serve/_private/proxy.py:534
(gRPCProxy).  A generic aio gRPC server inside an actor: any unary
method path ``/<anything>/<Method>`` routes to the application whose
route prefix matches the ``application`` request metadata (or, absent
that, ``/<Method>``).  Payloads are JSON bytes in/out — schema-free
like the HTTP proxy (the reference requires user protos + serve build;
this keeps the transport pluggable without a codegen step).  Dispatch
rides DeploymentHandle, so gRPC callers get the same pow-2 routing and
replica-death failover as HTTP and handle callers.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict

import ray_tpu
from ray_tpu.serve.traffic.config import RequestShedError

logger = logging.getLogger(__name__)

GRPC_PROXY_NAME = "_rt_serve_grpc_proxy"


@ray_tpu.remote
class GrpcProxyActor:
    def __init__(self, port: int = 9000):
        self._port = port
        self._server = None
        self._routes: Dict[str, Any] = {}
        self._routes_version = -1
        self._last_poll = 0.0
        self._last_full_read = 0.0
        self._published_version = None  # serve:routes pubsub bumps
        self._handles: Dict[str, Any] = {}
        self._controller = None

    async def start(self) -> int:
        import grpc

        if self._server is not None:
            return self._port

        # version-bump subscription: same protocol as the HTTP proxy —
        # the per-request poll skips its get_routes read while the
        # published version matches what we already hold
        try:
            from ray_tpu.core.runtime import get_runtime
            from ray_tpu.serve.controller import ROUTES_CHANNEL

            def _on_bump(msg: dict) -> None:
                self._published_version = msg.get("version")

            await get_runtime().subscribe_async(ROUTES_CHANNEL, _on_bump)
        except Exception:
            logger.debug("routes subscription failed; falling back to "
                         "polling", exc_info=True)

        outer = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                md = dict(handler_call_details.invocation_metadata or ())

                async def unary(request_bytes, context):
                    try:
                        return await outer._dispatch(
                            method, md, request_bytes
                        )
                    except RequestShedError as e:
                        # overload answer: RESOURCE_EXHAUSTED + machine-
                        # readable backoff hint in trailing metadata
                        context.set_trailing_metadata((
                            ("retry-after-s", f"{e.retry_after_s:.3f}"),
                        ))
                        await context.abort(
                            grpc.StatusCode.RESOURCE_EXHAUSTED, str(e)
                        )

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,  # raw bytes through
                    response_serializer=None,
                )

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((_Generic(),))
        bound = self._server.add_insecure_port(f"0.0.0.0:{self._port}")
        if bound == 0:  # grpc signals bind failure by returning port 0
            self._server = None
            raise RuntimeError(
                f"gRPC ingress could not bind port {self._port} "
                "(already in use?)"
            )
        await self._server.start()
        self._port = bound
        return bound

    # route state is controller-owned, polled versioned — same protocol
    # as the HTTP proxy (serve/proxy.py _poll_routes)
    def _poll_routes(self, force: bool = False):
        import time

        from ray_tpu.serve.proxy import ROUTE_POLL_S, ROUTE_RECHECK_S

        now = time.monotonic()
        if not force and now - self._last_poll < ROUTE_POLL_S:
            return
        self._last_poll = now
        if (
            not force
            and self._published_version is not None
            and self._published_version == self._routes_version
            and now - self._last_full_read < ROUTE_RECHECK_S
        ):
            return  # subscription says nothing moved: skip the read
        self._last_full_read = now
        if self._controller is None:
            from ray_tpu.serve.controller import get_or_create_controller

            self._controller = get_or_create_controller()
        routes = ray_tpu.get(
            self._controller.get_routes.remote(), timeout=30
        )
        if routes["version"] != self._routes_version:
            self._routes_version = routes["version"]
            new_routes = dict(routes.get("http_routes", {}))
            # drop ONLY handles whose prefix changed target — an
            # unrelated deploy must not discard warm replica routers
            # (same policy as serve/proxy.py)
            for p in list(self._handles):
                if new_routes.get(p) != self._routes.get(p):
                    self._handles.pop(p, None)
            self._routes = new_routes

    def _handle_for(self, prefix: str):
        h = self._handles.get(prefix)
        if h is None:
            from ray_tpu.serve.handle import DeploymentHandle

            app, deployment = self._routes[prefix]
            h = self._handles[prefix] = DeploymentHandle(
                self._controller, app, deployment
            )
            # wire-decoded args can never hold a DeploymentResponse:
            # skip the chained-arg scan in remote()
            h._args_known_plain = True
        return h

    async def _dispatch(self, method: str, metadata: Dict[str, str],
                        request_bytes: bytes) -> bytes:
        import asyncio
        import grpc  # noqa: F401

        route = metadata.get("application") or (
            "/" + method.rsplit("/", 1)[-1]
        )
        if not route.startswith("/"):
            route = "/" + route
        args: tuple = ()
        kwargs: Dict[str, Any] = {}
        if request_bytes:
            try:
                parsed = json.loads(request_bytes)
                if isinstance(parsed, dict):
                    kwargs = parsed
                else:
                    args = (parsed,)
            except (json.JSONDecodeError, UnicodeDecodeError):
                args = (request_bytes,)

        def _route_and_dispatch():
            self._poll_routes()
            prefix = route if route in self._routes else None
            if prefix is None:
                self._poll_routes(force=True)
                prefix = route if route in self._routes else None
            if prefix is None:
                return None
            handle = self._handle_for(prefix)
            # traffic-plane deployments dispatch on the io loop (same
            # policy as the HTTP proxy: the scheduler is loop-bound)
            r = handle._router
            if r._version < 0:
                try:
                    r._refresh(force=True)
                except Exception:
                    pass  # dispatch will surface routing errors
            if handle.traffic_config is not None:
                return ("traffic", handle)
            return handle.remote(*args, **kwargs)

        resp = await asyncio.get_running_loop().run_in_executor(
            None, _route_and_dispatch
        )
        if resp is None:
            raise RuntimeError(f"no serve application at route {route!r}")
        if isinstance(resp, tuple) and resp[0] == "traffic":
            resp = resp[1].remote(*args, **kwargs)
        value = await resp.result_async()
        if isinstance(value, bytes):
            return value
        return json.dumps(value, default=str).encode()

    async def ping(self) -> bool:
        return True


def start_grpc_proxy(port: int = 0) -> int:
    """Start (or reuse) the gRPC ingress; returns the bound port."""
    proxy = GrpcProxyActor.options(
        name=GRPC_PROXY_NAME, get_if_exists=True, lifetime="detached",
        num_cpus=0.1,
    ).remote(port)
    return ray_tpu.get(proxy.start.remote(), timeout=120)
