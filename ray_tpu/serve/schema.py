"""Declarative Serve config: deploy applications from a dict/YAML spec.

Role-equivalent of ray: python/ray/serve/schema.py (ServeDeploySchema /
ServeApplicationSchema) + `serve deploy` — an application is named by an
import path (``module:app`` where ``app`` is a bound Application), with
per-deployment overrides applied on top of the code-level settings:

    applications:
      - name: text_gen
        route_prefix: /generate
        import_path: my_project.serving:app
        deployments:
          - name: TextGen
            num_replicas: 4
            max_ongoing_requests: 16
    http_options:
      port: 8000

``serve.deploy_config(cfg)`` accepts the dict form;
``serve.deploy_config_file(path)`` reads YAML.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Dict, List, Optional

from ray_tpu.serve.deployment import Application, Deployment


@dataclasses.dataclass
class DeploymentOverride:
    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    autoscaling_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None
    # SLO/queueing policy override (serve/traffic TrafficConfig fields);
    # normalized by Deployment.__post_init__ like the decorator path
    traffic_config: Optional[dict] = None

    @staticmethod
    def from_dict(d: dict) -> "DeploymentOverride":
        known = {f.name for f in dataclasses.fields(DeploymentOverride)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown deployment option(s) {sorted(unknown)}"
            )
        return DeploymentOverride(**d)


@dataclasses.dataclass
class ApplicationSpec:
    name: str
    import_path: str
    route_prefix: Optional[str] = "/"
    deployments: List[DeploymentOverride] = dataclasses.field(
        default_factory=list
    )

    @staticmethod
    def from_dict(d: dict) -> "ApplicationSpec":
        return ApplicationSpec(
            name=d["name"],
            import_path=d["import_path"],
            route_prefix=d.get("route_prefix", "/"),
            deployments=[
                DeploymentOverride.from_dict(x)
                for x in d.get("deployments", [])
            ],
        )


def _import_target(import_path: str) -> Application:
    if ":" not in import_path:
        raise ValueError(
            f"import_path must be 'module:attribute', got {import_path!r}"
        )
    module_name, attr = import_path.split(":", 1)
    mod = importlib.import_module(module_name)
    target = getattr(mod, attr)
    if isinstance(target, Deployment):
        target = Application(target)
    if not isinstance(target, Application):
        raise TypeError(
            f"{import_path} resolved to {type(target).__name__}, expected a "
            "bound Application (deployment.bind(...))"
        )
    return target


def _apply_overrides(app: Application, overrides: List[DeploymentOverride]):
    by_name = {o.name: o for o in overrides}
    d = app.deployment
    o = by_name.get(d.name)
    if o is None:
        return app
    changes: Dict[str, Any] = {}
    if o.num_replicas is not None:
        changes["num_replicas"] = o.num_replicas
    if o.max_ongoing_requests is not None:
        changes["max_ongoing_requests"] = o.max_ongoing_requests
    if o.autoscaling_config is not None:
        changes["autoscaling_config"] = o.autoscaling_config
    if o.ray_actor_options is not None:
        changes["ray_actor_options"] = o.ray_actor_options
    if o.traffic_config is not None:
        changes["traffic_config"] = o.traffic_config
    return Application(d.options(**changes))


def deploy_config(config: dict) -> Dict[str, Any]:
    """Deploy every application in a declarative config dict; returns
    {app_name: handle}."""
    from ray_tpu.serve import api as serve_api

    handles = {}
    http_port = (config.get("http_options") or {}).get("port")
    for app_dict in config.get("applications", []):
        spec = ApplicationSpec.from_dict(app_dict)
        app = _import_target(spec.import_path)
        app = _apply_overrides(app, spec.deployments)
        handles[spec.name] = serve_api.run(
            app,
            name=spec.name,
            route_prefix=spec.route_prefix,
            http_port=http_port,
        )
    return handles


def deploy_config_file(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        return deploy_config(yaml.safe_load(f))
