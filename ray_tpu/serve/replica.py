"""Replica actor: hosts one copy of the user callable.

Role-equivalent of ray: python/ray/serve/_private/replica.py:231
(ReplicaActor, UserCallableWrapper:737).  Requests arrive as actor calls;
the replica tracks ongoing-request count (the router's pow-2 signal and
the controller's autoscaling signal).
"""

from __future__ import annotations

import inspect
from typing import Any

import ray_tpu


@ray_tpu.remote
class ReplicaActor:
    def __init__(self, func_or_class, init_args, init_kwargs, method_default):
        self._is_function = inspect.isfunction(func_or_class) or (
            callable(func_or_class) and not inspect.isclass(func_or_class)
        )
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
        self._method_default = method_default
        self._ongoing = 0
        self._total = 0
        self._streams = {}
        self._stream_seq = 0

    async def handle_request(self, method: str, args, kwargs) -> Any:
        self._ongoing += 1
        self._total += 1
        try:
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method or "__call__")
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            self._ongoing -= 1

    async def handle_request_stream_start(self, method: str, args, kwargs):
        """Start a streaming call: the target must return a (async)
        generator/iterable; chunks are pulled with stream_next (ray:
        serve streaming responses via ObjectRefGenerator — here a
        replica-pinned pull protocol over the actor transport)."""
        import inspect as _inspect

        self._ongoing += 1
        self._total += 1
        try:
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method or "__call__")
            result = target(*args, **kwargs)
            if _inspect.iscoroutine(result):
                result = await result
            if _inspect.isasyncgen(result):
                it = result
            elif hasattr(result, "__iter__") and not isinstance(
                result, (str, bytes, dict)
            ):
                it = iter(result)
            else:
                raise TypeError(
                    f"streaming call to {method!r} returned "
                    f"{type(result).__name__}, expected a generator/iterable"
                )
        except BaseException:
            self._ongoing -= 1
            raise
        self._stream_seq += 1
        sid = self._stream_seq
        self._streams[sid] = it
        return sid

    async def stream_next(
        self, sid: int, max_items: int = 8, budget_s: float = 0.5
    ) -> dict:
        """Pull up to max_items, returning EARLY once budget_s elapses
        after the first item — a slow generator yields partial batches
        promptly instead of blocking a full batch past the client's pull
        timeout."""
        import inspect as _inspect
        import time as _time

        it = self._streams.get(sid)
        if it is None:
            return {"items": [], "done": True}
        items = []
        done = False
        t0 = _time.monotonic()
        try:
            if _inspect.isasyncgen(it):
                for _ in range(max_items):
                    try:
                        items.append(await it.__anext__())
                    except StopAsyncIteration:
                        done = True
                        break
                    if _time.monotonic() - t0 > budget_s:
                        break
            else:
                for _ in range(max_items):
                    try:
                        items.append(next(it))
                    except StopIteration:
                        done = True
                        break
                    if _time.monotonic() - t0 > budget_s:
                        break
        except BaseException:
            self._streams.pop(sid, None)
            self._ongoing -= 1
            raise
        if done:
            self._streams.pop(sid, None)
            self._ongoing -= 1
        return {"items": items, "done": done}

    async def stream_cancel(self, sid: int) -> bool:
        if self._streams.pop(sid, None) is not None:
            self._ongoing -= 1
            return True
        return False

    async def queue_len(self) -> int:
        return self._ongoing

    async def stats(self) -> dict:
        import os

        return {
            "ongoing": self._ongoing,
            "total": self._total,
            "pid": os.getpid(),
        }

    async def reconfigure(self, user_config) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            out = fn(user_config)
            if inspect.iscoroutine(out):
                await out
        return True

    async def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            out = fn()
            if inspect.iscoroutine(out):
                out = await out
            return bool(out) if out is not None else True
        return True
