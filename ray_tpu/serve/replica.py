"""Replica actor: hosts one copy of the user callable.

Role-equivalent of ray: python/ray/serve/_private/replica.py:231
(ReplicaActor, UserCallableWrapper:737).  Requests arrive as actor calls;
the replica tracks ongoing-request count (the router's pow-2 signal and
the controller's autoscaling signal).
"""

from __future__ import annotations

import inspect
from typing import Any

import ray_tpu


@ray_tpu.remote
class ReplicaActor:
    def __init__(self, func_or_class, init_args, init_kwargs, method_default):
        self._is_function = inspect.isfunction(func_or_class) or (
            callable(func_or_class) and not inspect.isclass(func_or_class)
        )
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
        self._method_default = method_default
        self._ongoing = 0
        self._total = 0

    async def handle_request(self, method: str, args, kwargs) -> Any:
        self._ongoing += 1
        self._total += 1
        try:
            kwargs = self._apply_multiplex(kwargs)
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method or "__call__")
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            self._ongoing -= 1

    @staticmethod
    def _apply_multiplex(kwargs):
        """Pop the smuggled model id and expose it via the contextvar
        (ray: serve.get_multiplexed_model_id)."""
        from ray_tpu.serve import multiplex

        if multiplex.MODEL_ID_KWARG in kwargs:
            kwargs = dict(kwargs)
            multiplex.set_multiplexed_model_id(
                kwargs.pop(multiplex.MODEL_ID_KWARG)
            )
        return kwargs

    async def handle_request_stream(self, method: str, args, kwargs):
        """Streaming call: the target must return a (async) generator or
        iterable; items ride the core streaming-generator transport
        (num_returns="streaming" → ObjectRefGenerator), matching ray:
        serve's ObjectRefGenerator-backed streaming responses."""
        import inspect as _inspect

        self._ongoing += 1
        self._total += 1
        try:
            kwargs = self._apply_multiplex(kwargs)
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method or "__call__")
            result = target(*args, **kwargs)
            if _inspect.iscoroutine(result):
                result = await result
            if _inspect.isasyncgen(result):
                async for item in result:
                    yield item
            elif hasattr(result, "__iter__") and not isinstance(
                result, (str, bytes, dict)
            ):
                for item in result:
                    yield item
            else:
                raise TypeError(
                    f"streaming call to {method!r} returned "
                    f"{type(result).__name__}, expected a generator/iterable"
                )
        finally:
            self._ongoing -= 1

    async def queue_len(self) -> int:
        return self._ongoing

    async def stats(self) -> dict:
        import os

        return {
            "ongoing": self._ongoing,
            "total": self._total,
            "pid": os.getpid(),
        }

    async def reconfigure(self, user_config) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            out = fn(user_config)
            if inspect.iscoroutine(out):
                await out
        return True

    async def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            out = fn()
            if inspect.iscoroutine(out):
                out = await out
            return bool(out) if out is not None else True
        return True
