"""Replica actor: hosts one copy of the user callable.

Role-equivalent of ray: python/ray/serve/_private/replica.py:231
(ReplicaActor, UserCallableWrapper:737).  Requests arrive as actor calls;
the replica tracks ongoing-request count (the router's pow-2 signal and
the controller's autoscaling signal).
"""

from __future__ import annotations

import inspect
from typing import Any

import ray_tpu


def _resolve_handle_refs(value, app_name: str):
    """Swap HandleRef placeholders (left by serve.run's graph flatten)
    for live DeploymentHandles to sibling deployments of this app —
    model composition's injection point (reference:
    serve/_private/deployment_graph_build.py handle injection)."""
    from ray_tpu.serve.deployment import HandleRef

    if isinstance(value, HandleRef):
        from ray_tpu.serve.api import get_deployment_handle

        return get_deployment_handle(value.deployment_name, app_name)
    if isinstance(value, list):
        return [_resolve_handle_refs(v, app_name) for v in value]
    if isinstance(value, tuple):
        return tuple(_resolve_handle_refs(v, app_name) for v in value)
    if isinstance(value, dict):
        return {
            k: _resolve_handle_refs(v, app_name) for k, v in value.items()
        }
    return value


@ray_tpu.remote
class ReplicaActor:
    def __init__(
        self, func_or_class, init_args, init_kwargs, method_default,
        app_name: str = "",
    ):
        init_args = _resolve_handle_refs(tuple(init_args), app_name)
        init_kwargs = _resolve_handle_refs(dict(init_kwargs), app_name)
        self._is_function = inspect.isfunction(func_or_class) or (
            callable(func_or_class) and not inspect.isclass(func_or_class)
        )
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = func_or_class
        self._method_default = method_default
        self._ongoing = 0
        self._total = 0

    @staticmethod
    async def _resolve_chained(args, kwargs):
        """Resolve ObjectRef args left by response-chaining (an upstream
        DeploymentResponse passed into this call travels as its ref;
        it's nested inside the method-args tuple, so the task layer's
        top-level auto-resolution never sees it)."""
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()

        async def one(v):
            if isinstance(v, ObjectRef):
                return await rt.await_ref(v)
            if isinstance(v, list):
                return [await one(x) for x in v]
            if isinstance(v, tuple):
                return tuple([await one(x) for x in v])
            if isinstance(v, dict):
                return {k: await one(x) for k, x in v.items()}
            return v

        args = [await one(a) for a in args]
        kwargs = {k: await one(v) for k, v in kwargs.items()}
        return args, kwargs

    async def handle_request(self, method: str, args, kwargs) -> Any:
        self._ongoing += 1
        self._total += 1
        try:
            args, kwargs = await self._resolve_chained(args, kwargs)
            kwargs = self._apply_multiplex(kwargs)
            kwargs = self._apply_deadline(kwargs)
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method or "__call__")
            result = target(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
            return result
        finally:
            self._ongoing -= 1

    @staticmethod
    def _apply_multiplex(kwargs):
        """Pop the smuggled model id and expose it via the contextvar
        (ray: serve.get_multiplexed_model_id)."""
        from ray_tpu.serve import multiplex

        if multiplex.MODEL_ID_KWARG in kwargs:
            kwargs = dict(kwargs)
            multiplex.set_multiplexed_model_id(
                kwargs.pop(multiplex.MODEL_ID_KWARG)
            )
        return kwargs

    @staticmethod
    def _apply_deadline(kwargs):
        """Pop the traffic scheduler's remaining-SLO-budget kwarg and
        re-anchor it against THIS process's monotonic clock (budgets
        cross the wire as durations — clocks don't transfer), exposing
        the deadline via serve.traffic.get_request_deadline() for the
        LLM slot admitter and any deadline-aware user code."""
        from ray_tpu.serve.traffic import config as traffic_config

        if traffic_config.DEADLINE_KWARG in kwargs:
            import time

            kwargs = dict(kwargs)
            budget_s = kwargs.pop(traffic_config.DEADLINE_KWARG)
            traffic_config.set_request_deadline(
                time.monotonic() + float(budget_s)
            )
        else:
            # actor reuse: a prior deadline must not leak into a request
            # that arrived without one
            traffic_config.set_request_deadline(None)
        return kwargs

    async def handle_request_stream(self, method: str, args, kwargs):
        """Streaming call: the target must return a (async) generator or
        iterable; items ride the core streaming-generator transport
        (num_returns="streaming" → ObjectRefGenerator), matching ray:
        serve's ObjectRefGenerator-backed streaming responses."""
        import inspect as _inspect

        self._ongoing += 1
        self._total += 1
        try:
            args, kwargs = await self._resolve_chained(args, kwargs)
            kwargs = self._apply_multiplex(kwargs)
            kwargs = self._apply_deadline(kwargs)
            if self._is_function:
                target = self._callable
            else:
                target = getattr(self._callable, method or "__call__")
            result = target(*args, **kwargs)
            if _inspect.iscoroutine(result):
                result = await result
            if _inspect.isasyncgen(result):
                async for item in result:
                    yield item
            elif hasattr(result, "__iter__") and not isinstance(
                result, (str, bytes, dict)
            ):
                for item in result:
                    yield item
            else:
                raise TypeError(
                    f"streaming call to {method!r} returned "
                    f"{type(result).__name__}, expected a generator/iterable"
                )
        finally:
            self._ongoing -= 1

    async def queue_len(self) -> int:
        return self._ongoing

    async def stats(self) -> dict:
        import os

        return {
            "ongoing": self._ongoing,
            "total": self._total,
            "pid": os.getpid(),
        }

    async def reconfigure(self, user_config) -> bool:
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            out = fn(user_config)
            if inspect.iscoroutine(out):
                await out
        return True

    async def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if fn is not None:
            out = fn()
            if inspect.iscoroutine(out):
                out = await out
            return bool(out) if out is not None else True
        return True
