"""serve public API: run/delete/status/handles/shutdown.

Role-equivalent of ray: python/ray/serve/api.py (serve.run:545,
serve.start:66, serve.delete, serve.status).
"""

from __future__ import annotations

from typing import Optional

import ray_tpu
from ray_tpu.serve.controller import (
    CONTROLLER_NAME,
    get_or_create_controller,
)
from ray_tpu.serve.deployment import Application, Deployment, HandleRef
from ray_tpu.serve.handle import DeploymentHandle

PROXY_NAME = "SERVE_PROXY"

# Route state lives in the controller (versioned get_routes); the proxy
# polls it.  No driver-local route table — multiple drivers can deploy
# and delete apps without clobbering each other's routes.
_proxy_handle = None


def start(http_port: Optional[int] = None,
          grpc_port: Optional[int] = None):
    """Start the serve control plane (controller, plus HTTP/gRPC
    ingresses for whichever ports are given)."""
    controller = get_or_create_controller()
    ray_tpu.get(controller.ping.remote(), timeout=60)
    if http_port is not None:
        _get_or_create_proxy(http_port)
    if grpc_port is not None:
        from ray_tpu.serve.grpc_proxy import start_grpc_proxy

        start_grpc_proxy(grpc_port)
    return controller


def _get_or_create_proxy(port: int):
    global _proxy_handle
    from ray_tpu.serve.proxy import ProxyActor

    proxy = ProxyActor.options(
        name=PROXY_NAME, get_if_exists=True, lifetime="detached",
        num_cpus=0.1,
    ).remote(port)
    ray_tpu.get(proxy.start.remote(), timeout=60)
    _proxy_handle = proxy
    return proxy


def _flatten_graph(root: Application):
    """DFS over the bind graph: every reachable Application becomes one
    deployment (children before parents), nested Application references
    in init args are replaced by HandleRef placeholders, and name
    collisions (Model.bind('a') + Model.bind('b') → two nodes both
    named "Model") get _1/_2 suffixes — reference semantics
    (serve/_private/deployment_graph_build.py:65-69 + its name dedupe).
    Binding the SAME Application object twice shares one deployment.
    Cycles are rejected (a bind graph is a DAG by construction unless
    args were mutated after bind)."""
    import dataclasses as _dc

    name_counts: dict = {}
    used_names: set = set()
    assigned: dict = {}   # id(Application) -> final deployment name
    keepalive: list = []  # id() is only stable while the object lives
    visiting: set = set()
    order: list = []

    def substitute(v):
        if isinstance(v, Application):
            return HandleRef(visit(v))
        if isinstance(v, list):
            return [substitute(x) for x in v]
        if isinstance(v, tuple):
            return tuple(substitute(x) for x in v)
        if isinstance(v, dict):
            return {k: substitute(x) for k, x in v.items()}
        return v

    def visit(app: Application) -> str:
        key = id(app)
        if key in assigned:
            return assigned[key]
        if key in visiting:
            raise ValueError(
                f"cycle in deployment graph at {app.deployment.name!r}"
            )
        visiting.add(key)
        keepalive.append(app)
        d = app.deployment
        new_args = tuple(substitute(a) for a in d.init_args)
        new_kwargs = {k: substitute(v) for k, v in d.init_kwargs.items()}
        n = name_counts.get(d.name, 0)
        final = d.name if n == 0 else f"{d.name}_{n}"
        # a suffixed name can collide with a deployment GENUINELY named
        # that way (Model + Model + a real "Model_1") — skip forward
        # until free, or deploy_application would silently drop one
        while final in used_names:
            n += 1
            final = f"{d.name}_{n}"
        name_counts[d.name] = n + 1
        used_names.add(final)
        assigned[key] = final
        visiting.discard(key)
        order.append(
            _dc.replace(
                d, name=final, init_args=new_args, init_kwargs=new_kwargs
            )
        )
        return final

    root_name = visit(root)
    return order, root_name


def run(
    target: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = "/",
    http_port: Optional[int] = None,
    blocking: bool = False,
) -> DeploymentHandle:
    """Deploy an application — possibly a multi-deployment graph built by
    binding Applications into other deployments' init args — and return
    a handle to its ingress (root) deployment.

    (ray: serve/api.py:545 serve.run; the graph build is
    serve/_private/deployment_graph_build.py — nested ``m.bind()``
    results become DeploymentHandles injected into the parent replica.)
    """
    if isinstance(target, Deployment):
        target = Application(target)
    if not isinstance(target, Application):
        raise TypeError("serve.run expects Application (deployment.bind(...))")
    controller = get_or_create_controller()
    deployments, root_name = _flatten_graph(target)
    ray_tpu.get(
        controller.deploy_application.remote(name, deployments, root_name),
        timeout=120,
    )
    if route_prefix is not None:
        ray_tpu.get(
            controller.set_route_prefix.remote(route_prefix, name, root_name),
            timeout=60,
        )
        if http_port is not None:
            _get_or_create_proxy(http_port)
    return DeploymentHandle(controller, name, root_name)


def get_deployment_handle(
    deployment_name: str, app_name: str = "default"
) -> DeploymentHandle:
    return DeploymentHandle(
        get_or_create_controller(), app_name, deployment_name
    )


def get_app_handle(app_name: str = "default") -> DeploymentHandle:
    """Handle to the app's INGRESS deployment (the graph root for a
    composed app — not an arbitrary leaf)."""
    controller = get_or_create_controller()
    root = ray_tpu.get(controller.get_app_root.remote(app_name), timeout=30)
    if root is None:
        raise ValueError(f"no app named {app_name!r}")
    return DeploymentHandle(controller, app_name, root)


def delete(name: str):
    # delete_application also removes the app's HTTP routes; proxies pick
    # the change up on their next versioned poll.
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def status() -> dict:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.get_status.remote(), timeout=30)


def shutdown():
    """Tear down all serve actors."""
    global _proxy_handle
    from ray_tpu.core.actor import get_actor

    for app in list(status()):
        delete(app)
    _proxy_handle = None
    for actor_name in (PROXY_NAME, CONTROLLER_NAME):
        try:
            ray_tpu.kill(get_actor(actor_name))
        except Exception:
            pass
