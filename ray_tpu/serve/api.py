"""serve public API: run/delete/status/handles/shutdown.

Role-equivalent of ray: python/ray/serve/api.py (serve.run:545,
serve.start:66, serve.delete, serve.status).
"""

from __future__ import annotations

from typing import Optional

import ray_tpu
from ray_tpu.serve.controller import (
    CONTROLLER_NAME,
    get_or_create_controller,
)
from ray_tpu.serve.deployment import Application, Deployment
from ray_tpu.serve.handle import DeploymentHandle

PROXY_NAME = "SERVE_PROXY"

# Route state lives in the controller (versioned get_routes); the proxy
# polls it.  No driver-local route table — multiple drivers can deploy
# and delete apps without clobbering each other's routes.
_proxy_handle = None


def start(http_port: Optional[int] = None,
          grpc_port: Optional[int] = None):
    """Start the serve control plane (controller, plus HTTP/gRPC
    ingresses for whichever ports are given)."""
    controller = get_or_create_controller()
    ray_tpu.get(controller.ping.remote(), timeout=60)
    if http_port is not None:
        _get_or_create_proxy(http_port)
    if grpc_port is not None:
        from ray_tpu.serve.grpc_proxy import start_grpc_proxy

        start_grpc_proxy(grpc_port)
    return controller


def _get_or_create_proxy(port: int):
    global _proxy_handle
    from ray_tpu.serve.proxy import ProxyActor

    proxy = ProxyActor.options(
        name=PROXY_NAME, get_if_exists=True, lifetime="detached",
        num_cpus=0.1,
    ).remote(port)
    ray_tpu.get(proxy.start.remote(), timeout=60)
    _proxy_handle = proxy
    return proxy


def run(
    target: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = "/",
    http_port: Optional[int] = None,
    blocking: bool = False,
) -> DeploymentHandle:
    """Deploy an application; returns a handle to its (single) deployment.

    (Model-composition DAGs of multiple deployments bind through handles
    passed as init args; each deployment is then run separately.)
    """
    if isinstance(target, Deployment):
        target = Application(target)
    if not isinstance(target, Application):
        raise TypeError("serve.run expects Application (deployment.bind(...))")
    controller = get_or_create_controller()
    d = target.deployment
    ray_tpu.get(
        controller.deploy_application.remote(name, [d]), timeout=120
    )
    if route_prefix is not None:
        ray_tpu.get(
            controller.set_route_prefix.remote(route_prefix, name, d.name),
            timeout=60,
        )
        if http_port is not None:
            _get_or_create_proxy(http_port)
    return DeploymentHandle(controller, name, d.name)


def get_deployment_handle(
    deployment_name: str, app_name: str = "default"
) -> DeploymentHandle:
    return DeploymentHandle(
        get_or_create_controller(), app_name, deployment_name
    )


def get_app_handle(app_name: str = "default") -> DeploymentHandle:
    controller = get_or_create_controller()
    status = ray_tpu.get(controller.get_status.remote(), timeout=30)
    deployments = list(status.get(app_name, {}))
    if not deployments:
        raise ValueError(f"no app named {app_name!r}")
    return DeploymentHandle(controller, app_name, deployments[0])


def delete(name: str):
    # delete_application also removes the app's HTTP routes; proxies pick
    # the change up on their next versioned poll.
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def status() -> dict:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.get_status.remote(), timeout=30)


def shutdown():
    """Tear down all serve actors."""
    global _proxy_handle
    from ray_tpu.core.actor import get_actor

    for app in list(status()):
        delete(app)
    _proxy_handle = None
    for actor_name in (PROXY_NAME, CONTROLLER_NAME):
        try:
            ray_tpu.kill(get_actor(actor_name))
        except Exception:
            pass
