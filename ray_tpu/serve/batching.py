"""Dynamic request batching for deployment methods.

Role-equivalent of ray: python/ray/serve/batching.py:456 (@serve.batch):
concurrent calls to the decorated async method queue up; once
``max_batch_size`` requests are waiting — or the oldest has waited
``batch_wait_timeout_s`` — the wrapped function runs ONCE with a list of
the batched first-arguments and must return a list of results in the
same order, which are fanned back to the individual callers.

Usage (exactly the reference's shape)::

    @serve.deployment
    class Model:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        async def predict(self, inputs: List[np.ndarray]) -> List[float]:
            return model(np.stack(inputs)).tolist()

        async def __call__(self, x):
            return await self.predict(x)
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, owner, max_batch_size: int, wait_s: float):
        self._fn = fn
        self._owner = owner  # bound instance (None for free functions)
        self._max = max_batch_size
        self._wait_s = wait_s
        self._queue: List[tuple] = []  # (item, future)
        self._drainer: Optional[asyncio.Task] = None
        self._full = asyncio.Event()  # set by the submit filling a batch

    async def submit(self, item) -> Any:
        fut = asyncio.get_running_loop().create_future()
        self._queue.append((item, fut))
        if len(self._queue) >= self._max:
            self._full.set()
        if self._drainer is None or self._drainer.done():
            # covers both cold start and restart after idle — and, since
            # a dead drainer fails every future it stranded on the way
            # out (below), restart after a drainer crash too
            self._drainer = asyncio.get_running_loop().create_task(
                self._drain()
            )
        return await fut

    @staticmethod
    def _fan_out_exception(futs, exc: BaseException) -> None:
        """EVERY waiter of a failed batch learns the failure — a raising
        batch fn must never strand a future (the caller would await
        forever; through serve this wedges a replica slot)."""
        for f in futs:
            if not f.done():
                f.set_exception(exc)

    async def _drain(self):
        try:
            while self._queue:
                # exact wakeup: either the batch fills (submit sets the
                # event) or the window from the FIRST item elapses
                if len(self._queue) < self._max:
                    self._full.clear()
                    try:
                        await asyncio.wait_for(
                            self._full.wait(), timeout=self._wait_s
                        )
                    except asyncio.TimeoutError:
                        pass
                batch = self._queue[: self._max]
                del self._queue[: len(batch)]
                # reset between batches: a set() that filled THIS batch
                # must not wake the next (possibly partial) batch's wait
                # before its window — submit re-sets it if the remainder
                # already fills a batch
                self._full.clear()
                if len(self._queue) >= self._max:
                    self._full.set()
                items = [b[0] for b in batch]
                futs = [b[1] for b in batch]
                try:
                    if self._owner is not None:
                        results = await self._fn(self._owner, items)
                    else:
                        results = await self._fn(items)
                    if len(results) != len(items):
                        raise ValueError(
                            f"@serve.batch function returned "
                            f"{len(results)} results for {len(items)} "
                            f"inputs"
                        )
                except (asyncio.CancelledError, GeneratorExit) as e:
                    # the drainer task (or the batch fn from inside) was
                    # cancelled / closed: fail this batch's waiters, then
                    # honor the cancellation — the finally fans out to
                    # the rest of the queue
                    self._fan_out_exception(futs, e)
                    raise
                except Exception as e:  # noqa: BLE001 — fan-out
                    self._fan_out_exception(futs, e)
                    continue
                except BaseException as e:
                    # SystemExit/KeyboardInterrupt: tell this batch's
                    # waiters, then let the process-level signal
                    # propagate — the serve loop must not eat it
                    self._fan_out_exception(futs, e)
                    raise
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
        finally:
            # abnormal exit (cancellation, loop teardown): everything
            # still queued must fail fast rather than hang — the next
            # submit starts a fresh drainer either way
            if self._queue:
                pending = self._queue[:]
                del self._queue[: len(pending)]
                self._fan_out_exception(
                    [f for _, f in pending],
                    RuntimeError("@serve.batch drainer stopped with "
                                 "requests queued"),
                )


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 10,
    batch_wait_timeout_s: float = 0.01,
):
    """Decorator form of the reference's @serve.batch."""

    def wrap(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async def function")
        attr = f"__rt_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def method_wrapper(self, item):
            q = getattr(self, attr, None)
            if q is None:
                q = _BatchQueue(fn, self, max_batch_size,
                                batch_wait_timeout_s)
                setattr(self, attr, q)
            return await q.submit(item)

        # free-function form keeps one shared queue
        shared = _BatchQueue(fn, None, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        async def fn_wrapper(item):
            return await shared.submit(item)

        # methods are detected by their first parameter being `self` —
        # arity alone misclassifies free functions with extra defaulted
        # params (e.g. async def embed(items, normalize=True))
        import inspect

        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] in ("self", "cls")
        return method_wrapper if is_method else fn_wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
