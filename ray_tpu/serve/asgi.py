"""ASGI ingress: mount an existing ASGI app (FastAPI, Starlette, any
scope/receive/send callable) on a deployment.

Role-equivalent of ray: @serve.ingress (python/ray/serve/api.py:172) —
requests under the deployment's route prefix are dispatched through the
ASGI app with path routing intact, so an existing web app deploys
unmodified.  The transport differs from the reference (which runs
uvicorn inside the replica): here the HTTP proxy ships a compact request
dict over the actor RPC, and the replica drives the ASGI protocol
in-process — one hop, no per-replica HTTP server.
"""

from __future__ import annotations

from typing import Any, Callable, Dict


async def run_asgi_request(asgi_app: Callable, req: Dict[str, Any]) -> dict:
    """Drive one http-scope ASGI exchange; returns {status, headers,
    body} for the proxy to reconstruct the HTTP response."""
    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": req.get("method", "GET"),
        "scheme": "http",
        "path": req.get("path", "/"),
        "raw_path": req.get("path", "/").encode(),
        "query_string": (req.get("query_string") or "").encode(),
        "root_path": "",
        "headers": [
            (k.lower().encode(), v.encode())
            for k, v in req.get("headers") or []
        ],
        "server": ("ray-tpu-serve", 0),
        "client": ("127.0.0.1", 0),
    }
    body = req.get("body") or b""
    state = {"status": 500, "headers": [], "parts": [], "sent_request": False}

    async def receive():
        if not state["sent_request"]:
            state["sent_request"] = True
            return {"type": "http.request", "body": body, "more_body": False}
        return {"type": "http.disconnect"}

    async def send(message):
        t = message["type"]
        if t == "http.response.start":
            state["status"] = message["status"]
            state["headers"] = [
                (k.decode("latin1"), v.decode("latin1"))
                for k, v in message.get("headers") or []
            ]
        elif t == "http.response.body":
            state["parts"].append(bytes(message.get("body") or b""))

    await asgi_app(scope, receive, send)
    return {
        "status": state["status"],
        "headers": state["headers"],
        "body": b"".join(state["parts"]),
    }


def ingress(asgi_app: Callable):
    """Class decorator: ``@serve.deployment`` + ``@serve.ingress(app)``
    routes every HTTP request under the deployment's prefix through
    ``asgi_app``.  The decorated class's instance state coexists with
    the app (lifecycle, handles in init args, etc.)."""

    def wrap(cls):
        if not isinstance(cls, type):
            raise TypeError(
                "@serve.ingress decorates the deployment CLASS "
                "(apply @serve.deployment above it)"
            )

        async def __asgi_handle__(self, req: Dict[str, Any]) -> dict:
            return await run_asgi_request(type(self).__rt_asgi_app__, req)

        cls.__rt_asgi_app__ = asgi_app
        cls.__rt_is_asgi__ = True
        cls.__asgi_handle__ = __asgi_handle__
        return cls

    return wrap
