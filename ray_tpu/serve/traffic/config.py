"""Traffic-plane configuration, shed error, and per-request deadline
context.

The traffic plane (scheduler + admission + queue-driven autoscaling)
activates for a deployment when its ``Deployment.traffic_config`` is
set; without one, serve behaves exactly as before (direct pow-2
dispatch, no admission control) — the depth-1 path is untouched.

Deadlines cross the proxy→replica boundary as a REMAINING BUDGET in
seconds (``DEADLINE_KWARG``), not an absolute timestamp: monotonic
clocks don't transfer between processes and wall clocks skew.  The
replica re-anchors the budget against its own monotonic clock on
arrival and exposes it via ``get_request_deadline()`` (same contextvar
pattern as serve.multiplex), which the LLM engine's slot admitter uses
for earliest-deadline-first admission.
"""

from __future__ import annotations

import contextvars
import dataclasses
from typing import Optional

#: kwarg under which the scheduler smuggles the remaining SLO budget
#: (seconds, float) to the replica; popped before the user callable
#: sees kwargs (exactly like multiplex.MODEL_ID_KWARG).
DEADLINE_KWARG = "__rt_slo_remaining_s__"


@dataclasses.dataclass
class TrafficConfig:
    """Per-deployment SLO + queueing policy (reference shape: the ray
    serve request-router/autoscaling knobs, collapsed to the queue
    model architecture.md documents).

    ``slo_ms`` is the admission→completion budget: requests predicted
    (or observed) to miss it are shed with a 503 + Retry-After instead
    of queueing unboundedly.
    """

    #: per-request deadline budget, admission to completion
    slo_ms: float = 1000.0
    #: hard cap of queued (admitted, undispatched) requests per
    #: deployment per routing process — the bounded queue
    max_queue_depth: int = 256
    #: floor for Retry-After hints on shed responses
    shed_retry_after_s: float = 1.0
    #: queue depth per replica the autoscaler treats as "backed up"
    #: (scale up on sustained depth past this)
    target_queue_depth_per_replica: float = 4.0
    #: how often each scheduler pushes depth/rate stats to the
    #: controller (the autoscaling signal)
    stats_push_interval_s: float = 0.5
    #: scale-down grace: a draining replica finishes its in-flight
    #: work for at most this long before it is stopped anyway
    drain_timeout_s: float = 30.0

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_wire(d: Optional[dict]) -> "Optional[TrafficConfig]":
        if d is None:
            return None
        if isinstance(d, TrafficConfig):
            return d
        known = {f.name for f in dataclasses.fields(TrafficConfig)}
        return TrafficConfig(**{k: v for k, v in d.items() if k in known})


class RequestShedError(Exception):
    """Raised when admission control refuses (or the scheduler expires)
    a request instead of queueing it past the SLO budget.  Carries the
    Retry-After hint the proxies surface (HTTP 503 / gRPC
    RESOURCE_EXHAUSTED)."""

    def __init__(self, reason: str, retry_after_s: float = 1.0,
                 deployment: str = ""):
        super().__init__(
            f"request shed{f' for {deployment!r}' if deployment else ''}: "
            f"{reason} (retry after {retry_after_s:.2f}s)"
        )
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        self.deployment = deployment

    def __reduce__(self):
        return (
            RequestShedError,
            (self.reason, self.retry_after_s, self.deployment),
        )


_request_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "rt_serve_request_deadline", default=None
)


def set_request_deadline(deadline_monotonic: Optional[float]) -> None:
    """Replica-side: record this request's deadline (time.monotonic()
    reference frame of THIS process)."""
    _request_deadline.set(deadline_monotonic)


def get_request_deadline() -> Optional[float]:
    """Deadline of the current request as a local ``time.monotonic()``
    timestamp, or None when the caller attached no SLO (direct handle
    calls, deployments without a traffic config)."""
    return _request_deadline.get()
