"""Admission control + load shedding for one deployment's traffic.

The Podracer central-batcher lesson (arxiv 2104.06272) applied to
serving: the way to keep replicas saturated WITHOUT unbounded latency
is a short bounded queue in front of them — deep enough to ride out
service-time jitter, shallow enough that everything admitted still
makes its deadline.  This module is the policy half: given the queue
depth, the in-flight count, and an EWMA of observed completion
throughput, decide admit-or-shed and compute the Retry-After hint.

Shed decisions are O(1) arithmetic on counters the scheduler already
maintains — no locks, no RPCs — so the admission check sits on the
proxy's per-request hot path without showing up in depth-1 latency.
"""

from __future__ import annotations

import time
from typing import Optional

from ray_tpu.serve.traffic.config import RequestShedError, TrafficConfig

#: EWMA horizon for the service-rate estimate, in completions.  Small
#: enough to track load shifts within a second of steady traffic, big
#: enough that one slow outlier doesn't crater the rate.
_RATE_ALPHA = 0.1

#: a cold controller (no completions observed yet) admits on depth
#: alone — shedding on a rate estimate of zero would refuse the very
#: requests that would have warmed it
_MIN_OBSERVATIONS = 4


class AdmissionController:
    """Per-deployment, per-routing-process admission policy.

    Owned by a RequestScheduler; all methods run on that scheduler's
    event loop (no locking).  Tracks:

    - ``inflight``/``queued`` — updated by the scheduler
    - completion-rate EWMA (requests/s across all replicas, as observed
      from THIS process)
    - shed/admit/complete counters for the stats push + bench
    """

    def __init__(self, config: TrafficConfig, deployment: str = ""):
        self.config = config
        self.deployment = deployment
        self.queued = 0
        self.inflight = 0
        # service-rate EWMA state
        self._rate: float = 0.0          # completions/s
        self._last_complete_t: Optional[float] = None
        self._completions = 0
        # counters (monotonic; the stats push sends deltas)
        self.admitted_total = 0
        self.shed_total = 0
        self.completed_total = 0
        self.expired_total = 0  # admitted but deadline passed in queue

    # -- signal updates (scheduler-driven) -------------------------------
    def on_admit(self) -> None:
        self.queued += 1
        self.admitted_total += 1

    def on_dispatch(self) -> None:
        self.queued -= 1
        self.inflight += 1

    def on_expire(self) -> None:
        """An admitted request's deadline passed while it waited."""
        self.queued -= 1
        self.expired_total += 1
        self.shed_total += 1

    def on_complete(self, now: Optional[float] = None) -> None:
        self.inflight -= 1
        self.completed_total += 1
        self._completions += 1
        t = time.monotonic() if now is None else now
        if self._last_complete_t is not None:
            dt = t - self._last_complete_t
            if dt > 0:
                inst = 1.0 / dt
                self._rate = (
                    inst if self._rate == 0.0
                    else (1 - _RATE_ALPHA) * self._rate + _RATE_ALPHA * inst
                )
        self._last_complete_t = t

    # -- policy ----------------------------------------------------------
    @property
    def service_rate(self) -> float:
        """Observed completions/s (EWMA), 0.0 while cold."""
        if self._completions < _MIN_OBSERVATIONS:
            return 0.0
        return self._rate

    def predicted_delay_s(self) -> float:
        """Expected queueing delay for the NEXT admitted request: the
        work ahead of it (queued, plus whatever is in flight beyond
        what completes "for free" this instant) divided by the observed
        drain rate.  0.0 while cold — depth caps govern the cold
        start."""
        rate = self.service_rate
        if rate <= 0.0:
            return 0.0
        return self.queued / rate

    def check(self) -> None:
        """Admit or raise RequestShedError.  Two independent trips:

        - depth: the bounded queue is full (backpressure made visible
          instead of buffering unboundedly), or
        - SLO: the predicted queueing delay alone already exceeds the
          end-to-end budget, so admitting would only manufacture a
          deadline miss the replica pays compute for.
        """
        c = self.config
        if self.queued >= c.max_queue_depth:
            self.shed_total += 1
            raise RequestShedError(
                f"queue depth {self.queued} at cap {c.max_queue_depth}",
                retry_after_s=self._retry_after(),
                deployment=self.deployment,
            )
        slo_s = c.slo_ms / 1000.0
        predicted = self.predicted_delay_s()
        if predicted > slo_s:
            self.shed_total += 1
            raise RequestShedError(
                f"predicted queueing delay {predicted * 1000:.0f}ms "
                f"exceeds the {c.slo_ms:.0f}ms SLO budget",
                retry_after_s=self._retry_after(),
                deployment=self.deployment,
            )

    def _retry_after(self) -> float:
        """Hint: time for the current backlog to drain to half the SLO
        budget at the observed rate, floored by config."""
        c = self.config
        rate = self.service_rate
        if rate <= 0.0:
            return c.shed_retry_after_s
        target_depth = max(1.0, rate * (c.slo_ms / 2000.0))
        excess = self.queued - target_depth
        return max(c.shed_retry_after_s, excess / rate)

    def expired_retry_after(self) -> float:
        return self._retry_after()

    def snapshot(self) -> dict:
        return {
            "queued": self.queued,
            "inflight": self.inflight,
            "rate": round(self.service_rate, 3),
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "expired_total": self.expired_total,
            "completed_total": self.completed_total,
        }
