"""SLO-aware request scheduler: the serve-routing twin of the batched
task plane's per-tick accumulator (core/rpc.py `_send_soon` /
core/runtime.py `_submit_to_loop`).

Requests submitted within one event-loop tick accumulate into a
deadline-ordered queue and dispatch together in ONE flush callback —
so a burst of proxy requests rides the rpc layer's per-tick BATCH
frame coalescing to the replicas (every `.remote()` issued inside the
flush lands in the same tick, hence the same wire frame per
connection), and the flush can order by deadline before anything
commits to a replica.  Latency-neutral at depth 1 by the same
construction as the task plane: the flush runs via ``loop.call_soon``
before the loop can sleep, never on a timer.

Differences from the pow-2 router (handle.py) this sits in front of:

- **central queue, full knowledge**: the scheduler owns per-replica
  in-flight counts for every request IT dispatched, picks the least
  loaded replica, and holds requests past ``max_ongoing_requests``
  per replica in a bounded queue instead of piling them onto the
  replica's mailbox (the Podracer central-batcher shape).
- **EDF order**: dispatch is earliest-deadline-first, so a tight-SLO
  request admitted behind a lax one overtakes it at the queue.
- **deadline expiry**: a request whose deadline passes while queued is
  shed (fast 503) rather than dispatched — the replica never spends
  compute on a response the client already gave up on.
- **bounded everything**: admission (admission.py) refuses requests
  past the depth cap or predicted-delay budget, which is what honors
  the transport's `send_backlog` discipline at this layer — load is
  shed at the door instead of buffered without bound anywhere below.

Backpressure audit (RT110/RT111): the scheduler never enqueues onto
``Connection.call_soon`` itself — dispatch rides ``.remote()``, whose
actor pump polices ``send_backlog`` (the baselined runtime.py site) —
and its own queue is bounded by admission, so no unbounded buffering
is introduced above the transport either.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import logging
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.serve.traffic.admission import AdmissionController
from ray_tpu.serve.traffic.config import (
    DEADLINE_KWARG,
    RequestShedError,
    TrafficConfig,
)

logger = logging.getLogger(__name__)

#: replica snapshot staleness bound (mirrors handle.ROUTE_REFRESH_S)
_SNAPSHOT_REFRESH_S = 1.0


class _QueuedRequest:
    __slots__ = (
        "deadline", "seq", "method", "args", "kwargs", "future",
        "enqueue_t",
    )

    def __init__(self, deadline, seq, method, args, kwargs, future,
                 enqueue_t):
        self.deadline = deadline
        self.seq = seq
        self.method = method
        self.args = args
        self.kwargs = kwargs
        self.future = future
        self.enqueue_t = enqueue_t

    def __lt__(self, other):  # heapq ordering: EDF, FIFO within a tie
        return (self.deadline, self.seq) < (other.deadline, other.seq)


class RequestScheduler:
    """Per-deployment, per-process scheduler.  Loop-only: every method
    except the stats snapshot must run on the event loop that created
    it (the proxy actor's io loop, or a composing replica's)."""

    def __init__(self, router, controller, app: str, deployment: str,
                 config: TrafficConfig):
        self._loop = asyncio.get_running_loop()
        self._router = router  # handle.Router: replica list + refresh
        self._controller = controller
        self._app = app
        self._deployment = deployment
        self.config = config
        self.admission = AdmissionController(config, deployment)
        self._heap: List[_QueuedRequest] = []
        self._seq = itertools.count()
        # controller-side stats key: several routing processes report
        # the same deployment, and the controller sums across reporters
        # — id(self) would be a per-process heap address that can
        # collide across processes and silently clobber
        self._reporter_id = uuid.uuid4().hex
        # wire dict this scheduler's config was built from; the handle
        # layer compares it against the router's current entry (identity
        # first) and applies redeploy-time policy changes in place
        self._wire_config: Optional[dict] = None
        self._inflight: Dict[Any, int] = {}  # replica -> scheduler-dispatched
        self._flush_scheduled = False
        self._expiry_timer: Optional[asyncio.TimerHandle] = None
        self._refreshing = False
        self._last_snapshot_t = 0.0
        self._last_stats_push = 0.0
        self._last_pushed: dict = {}

    # -- submit (the handle calls this on the loop) ----------------------
    def submit(self, method: str, args, kwargs,
               slo_ms: Optional[float] = None) -> "asyncio.Future":
        """Admit (or shed) one request; returns a future resolving to
        ``(replica, ref)`` at dispatch time.  Raises RequestShedError
        synchronously when admission refuses."""
        self.admission.check()  # raises RequestShedError on refusal
        now = time.monotonic()
        budget_s = (slo_ms if slo_ms is not None
                    else self.config.slo_ms) / 1000.0
        req = _QueuedRequest(
            deadline=now + budget_s,
            seq=next(self._seq),
            method=method,
            args=args,
            kwargs=kwargs,
            future=asyncio.get_running_loop().create_future(),
            enqueue_t=now,
        )
        heapq.heappush(self._heap, req)
        self.admission.on_admit()
        self._schedule_flush()
        return req.future

    # -- per-tick flush --------------------------------------------------
    def _schedule_flush(self) -> None:
        if not self._flush_scheduled:
            # loop-confined despite the sync signature: submit() runs on
            # the loop (get_running_loop above) and _flush rides
            # call_soon on that same loop — rtrace's caller-plane seed
            # for public sync methods over-approximates here
            # rtlint: disable-next=RT301
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)

    def _flush(self) -> None:
        """Dispatch everything dispatchable, EDF order: shed expired
        requests, fill replica capacity least-loaded-first, leave the
        rest queued for the next capacity release / deadline sweep."""
        # loop-confined; see _schedule_flush
        # rtlint: disable-next=RT301
        self._flush_scheduled = False
        now = time.monotonic()
        replicas = self._replica_snapshot(now)
        max_ongoing = getattr(self._router, "max_ongoing", 100) or 100
        while self._heap:
            req = self._heap[0]
            if req.future.done():  # caller went away (cancelled)
                heapq.heappop(self._heap)
                self.admission.queued -= 1
                continue
            if req.deadline <= now:
                heapq.heappop(self._heap)
                self.admission.on_expire()
                req.future.set_exception(RequestShedError(
                    "deadline expired after "
                    f"{(now - req.enqueue_t) * 1000:.0f}ms in queue",
                    retry_after_s=self.admission.expired_retry_after(),
                    deployment=self._deployment,
                ))
                continue
            replica = self._pick(replicas, max_ongoing)
            if replica is None:
                break  # no capacity: stays queued, EDF order preserved
            heapq.heappop(self._heap)
            self._dispatch(req, replica, now)
        self._arm_expiry_timer(now)
        self._maybe_push_stats(now)

    def _pick(self, replicas: list, max_ongoing: int):
        """Least-loaded replica with a free slot (central-batcher pick:
        the scheduler knows every in-flight it created, so it beats
        pow-2 sampling at equalizing load under fan-in)."""
        best = None
        best_n = max_ongoing
        for r in replicas:
            n = self._inflight.get(r, 0)
            if n < best_n:
                best, best_n = r, n
        return best

    def _dispatch(self, req: _QueuedRequest, replica, now: float) -> None:
        kwargs = dict(req.kwargs)
        kwargs[DEADLINE_KWARG] = req.deadline - now  # remaining budget
        try:
            ref = replica.handle_request.remote(
                req.method, req.args, kwargs
            )
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            self.admission.queued -= 1
            if not req.future.done():
                req.future.set_exception(e)
            return
        self._inflight[replica] = self._inflight.get(replica, 0) + 1
        self._router.note_dispatch(replica)  # pow-2 load signal parity
        self.admission.on_dispatch()
        if not req.future.done():
            req.future.set_result((replica, ref))
        # completion waiter: releases the slot + feeds the service-rate
        # EWMA + re-flushes (the continuous-batching admit edge) without
        # materializing the value in this process
        asyncio.get_running_loop().create_task(
            self._await_completion(replica, ref)
        )

    async def _await_completion(self, replica, ref) -> None:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        try:
            if asyncio.get_running_loop() is rt._loop:
                await rt.await_ref_completion(ref)
            else:
                # scheduler on a foreign loop (driver asyncio.run): the
                # runtime's completion futures are bound to its io loop,
                # so bridge through the thread-safe future
                await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                    rt.await_ref_completion(ref), rt._loop
                ))
        except Exception:
            pass  # errored completion still frees the slot
        n = self._inflight.get(replica, 0)
        if n <= 1:
            self._inflight.pop(replica, None)
        else:
            self._inflight[replica] = n - 1
        self.admission.on_complete()
        if self._heap:
            self._schedule_flush()

    # -- replica snapshot (never blocks the loop) ------------------------
    def _replica_snapshot(self, now: float) -> list:
        with self._router._lock:
            replicas = list(self._router._replicas)
        if not replicas or now - self._last_snapshot_t > _SNAPSHOT_REFRESH_S:
            self._last_snapshot_t = now
            if not self._refreshing:
                self._refreshing = True
                loop = asyncio.get_running_loop()

                def _refresh():
                    try:
                        self._router._refresh(force=not replicas)
                    except Exception:
                        logger.debug("route refresh failed", exc_info=True)

                fut = loop.run_in_executor(None, _refresh)

                def _done(_f):
                    self._refreshing = False
                    if self._heap:
                        self._schedule_flush()

                fut.add_done_callback(_done)
        return replicas

    def drop_replica(self, replica) -> None:
        """Replica died: forget its slots (failover redispatch is the
        response's job; the scheduler only frees capacity)."""
        self._inflight.pop(replica, None)
        if self._heap:
            self._schedule_flush()

    def drop_replica_threadsafe(self, replica) -> None:
        """Off-loop twin (the router's failover path runs on driver /
        executor threads)."""
        try:
            self._loop.call_soon_threadsafe(self.drop_replica, replica)
        except RuntimeError:
            pass  # loop closing

    # -- deadline sweep --------------------------------------------------
    def _arm_expiry_timer(self, now: float) -> None:
        if self._expiry_timer is not None:
            self._expiry_timer.cancel()
            self._expiry_timer = None
        if not self._heap:
            return
        delay = max(0.001, self._heap[0].deadline - now)
        self._expiry_timer = asyncio.get_running_loop().call_later(
            delay, self._expiry_sweep
        )

    def _expiry_sweep(self) -> None:
        self._expiry_timer = None
        self._schedule_flush()

    # -- autoscaling signal ----------------------------------------------
    def _maybe_push_stats(self, now: float) -> None:
        """Throttled fire-and-forget depth/rate report to the
        controller — the queue-driven autoscaling signal.  Rides a
        plain actor call on the batched task plane; losing one report
        is harmless (the next flush resends)."""
        if self._controller is None:
            return
        if now - self._last_stats_push < self.config.stats_push_interval_s:
            return
        snap = self.admission.snapshot()
        if snap == self._last_pushed and snap["queued"] == 0:
            return  # idle steady state: nothing to say
        self._last_stats_push = now
        self._last_pushed = snap
        try:
            # telemetry push, audited fire-and-forget: the reply is
            # nothing, errors only mean a controller restart (the next
            # push re-reports), and awaiting would serialize the flush
            # on a controller round trip
            # rtlint: disable-next=RT105
            self._controller.report_traffic_stats.remote(
                self._app, self._deployment, self._reporter_id, snap
            )
        except Exception:
            logger.debug("traffic stats push failed", exc_info=True)

    def stats(self) -> dict:
        """Thread-safe-enough snapshot for benches/tests."""
        out = self.admission.snapshot()
        out["scheduler_inflight"] = sum(self._inflight.values())
        return out
