"""serve.traffic: the SLO-aware traffic plane between proxies and
replicas.

Four pieces (see docs/architecture.md "Serve traffic plane"):

- ``RequestScheduler`` (scheduler.py) — per-deployment EDF dispatch
  over the batched task plane, per-tick accumulation like the rpc
  accumulator, bounded central queue with full in-flight knowledge.
- ``AdmissionController`` (admission.py) — depth- and predicted-delay
  based load shedding with Retry-After hints.
- queue-depth-driven replica autoscaling — the controller side
  (serve/controller.py) consumes the schedulers' stats pushes and
  drains replicas before stopping them.
- the LLM slot admitter (serve/llm.py) consumes the per-request
  deadline this package smuggles to replicas.

Activation is per deployment: set ``traffic_config`` on
``@serve.deployment`` and every proxy/handle route to it gains
admission control and SLO-ordered dispatch; deployments without one
keep the direct pow-2 path, bit-for-bit.
"""

from ray_tpu.serve.traffic.admission import AdmissionController  # noqa: F401
from ray_tpu.serve.traffic.config import (  # noqa: F401
    DEADLINE_KWARG,
    RequestShedError,
    TrafficConfig,
    get_request_deadline,
    set_request_deadline,
)
from ray_tpu.serve.traffic.scheduler import RequestScheduler  # noqa: F401
