"""TPU compute kernels: ring/flash attention, fused ops (Pallas + XLA)."""

from ray_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_manual,
)
