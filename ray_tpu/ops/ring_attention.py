"""Ring attention: causal attention with the sequence sharded over `sp`.

Long-context capability absent from the reference (SURVEY.md §5
"long-context": verified no ring/context-parallel code exists there) and
required here as a first-class feature.  Each sp shard holds a sequence
block; KV blocks rotate around the ICI ring (lax.ppermute) while every
shard accumulates its queries' attention online in log-sum-exp form —
so peak memory is O(S/n) per chip and the KV transfer overlaps compute.

Numerics follow flash attention: f32 running (max, sumexp, out)
accumulators, mask applied multiplicatively after exponentiation so
fully-masked (future) blocks contribute exactly zero.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ray_tpu.ops.attention import dense_attention
from ray_tpu.parallel.collectives import ring_permute
from ray_tpu.parallel.mesh import DATA_AXES, SP_AXIS, TP_AXIS, current_mesh


def _block_update(carry, kv, *, q, q_pos, k_pos, scale):
    """One online-softmax update with the resident KV block."""
    o, m, l = carry
    k, v = kv
    s = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    mask = (q_pos[:, None] >= k_pos[None, :])[None, None, :, :]
    s = jnp.where(mask, s, -1e30)
    m_new = jnp.maximum(m, s.max(-1))
    p = jnp.exp(s - m_new[..., None]) * mask
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32)
    )
    return o, m_new, l


def ring_attention_manual(q, k, v, *, axis_name: str = SP_AXIS):
    """Ring attention body; must run under shard_map with ``axis_name``.

    q, k, v: (B, S_local, H, D).  Returns (B, S_local, H, D) in q.dtype.
    """
    B, S, H, D = q.shape
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32)

    o = jnp.zeros((B, H, S, D), jnp.float32)
    m = jnp.full((B, H, S), -1e30, jnp.float32)
    l = jnp.zeros((B, H, S), jnp.float32)
    q_pos = my * S + jnp.arange(S)

    def step(carry, t):
        o, m, l, k, v = carry
        src = (my - t) % n  # which shard's KV we hold at step t
        k_pos = src * S + jnp.arange(S)
        o, m, l = _block_update(
            (o, m, l), (k, v), q=qf, q_pos=q_pos, k_pos=k_pos, scale=scale
        )
        # Rotate KV to the next neighbor (final rotation feeds nothing).
        k = ring_permute(k, axis_name, shift=1)
        v = ring_permute(v, axis_name, shift=1)
        return (o, m, l, k, v), None

    (o, m, l, _, _), _ = lax.scan(
        step,
        (o, m, l, k.astype(q.dtype), v.astype(q.dtype)),
        jnp.arange(n),
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _resolve_mesh():
    """The mesh to ring over: the JAX-ambient mesh (jax.set_mesh) if one
    is active — the standard way users bind a mesh — else the framework's
    make_mesh global.  Ambient wins so a stale make_mesh global can't
    shadow the mesh the surrounding program is actually compiled for."""
    try:
        ambient = jax.sharding.get_mesh()
        if ambient is not None and SP_AXIS in getattr(ambient, "shape", {}):
            if not getattr(ambient, "empty", False):
                return ambient
    except Exception:
        pass
    return current_mesh()


def ring_attention(q, k, v):
    """Causal ring attention over the current mesh's sp axis.

    Falls back to the equivalent dense computation when no mesh is active
    or sp == 1 (e.g. single-device eval), so model code can select
    attention_impl="ring" unconditionally.
    """
    mesh = _resolve_mesh()
    if mesh is None or mesh.shape.get(SP_AXIS, 1) == 1:
        return dense_attention(q, k, v)
    spec = P(DATA_AXES, SP_AXIS, TP_AXIS, None)
    fn = jax.shard_map(
        partial(ring_attention_manual, axis_name=SP_AXIS),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # The scan carry starts device-invariant and becomes varying after
        # the first ppermute; skip the static vma check rather than pcast
        # every accumulator.
        check_vma=False,
    )
    return fn(q, k, v)
