"""Baseline attention kernels (XLA einsum path).

The dense causal kernel lives here — not in the model zoo — so both
models and the ring/flash variants share one implementation.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def dense_attention(q, k, v, *, start_pos: int = 0, window: int = 0):
    """Causal attention, f32 softmax.  q,k,v: (B, S, H, D).

    ``start_pos`` offsets query positions for decode-time use (queries
    are a suffix of the key sequence).  ``window`` > 0 limits each
    query to the last ``window`` keys (Mistral-style sliding-window
    attention: position t attends to (t-window, t]; memory-for-range
    tradeoff long-context models use).
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(D)
    q_pos = jnp.arange(Sq)[:, None] + start_pos
    k_pos = jnp.arange(Sk)[None, :]
    mask = q_pos >= k_pos
    if window > 0:
        mask &= k_pos > q_pos - window
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
