"""Flash attention for TPU as a Pallas kernel.

Causal multi-head attention that never materializes the (S, S) score
matrix: queries are processed in blocks against KV blocks with an online
log-sum-exp softmax, so per-core live memory is O(block² + block·D) VMEM
and HBM traffic is O(S·D) instead of O(S²).  This is the single biggest
HBM-bandwidth lever for transformer training on TPU — the dense einsum
path writes + rereads ~400 MB of f32 scores per layer for (B=8, H=12,
S=1024) while this kernel writes only the (B, H, S) log-sum-exp.

Layout: q, k, v are (B, S, H, D) (model-native).  The kernel grid is
(B, H, nq[, nk]) and BlockSpecs pick (1, blk, 1, D) slices, so no
transposes are needed on the HBM side.

Backward follows the flash-attention-2 recipe: save (o, lse), compute
delta = rowsum(do ⊙ o), then one kernel accumulates dq over KV blocks
and another accumulates (dk, dv) over Q blocks, recomputing p = exp(s −
lse) on the fly.

Role-equivalent to the reference's fused GPU attention paths (the
reference delegates to torch/cutlass; here the MXU/VMEM design is
original).  Falls back to the dense einsum on non-TPU backends so tests
run on CPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, blk_q, blk_k):
    """Grid (B, H, nq, nk); kv innermost.  Accumulators live in the o/lse
    output blocks (revisited across the nk dimension) — m and l are packed
    into lse_ref's two rows until the final kv step collapses them."""
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)
        lse_ref[0, 0, 0, :] = jnp.full((blk_q,), NEG_INF, jnp.float32)  # m
        lse_ref[0, 0, 1, :] = jnp.zeros((blk_q,), jnp.float32)  # l

    # Causal: kv block ki overlaps q block qi iff ki*blk_k <= qi*blk_q + blk_q - 1.
    @pl.when(ki * blk_k < (qi + 1) * blk_q)
    def _step():
        q = q_ref[0, 0, :, :]  # (blk_q, D)
        k = k_ref[0, 0, :, :]  # (blk_k, D)
        v = v_ref[0, 0, :, :]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # (blk_q, blk_k)
        q_pos = qi * blk_q + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0
        )
        k_pos = ki * blk_k + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1
        )
        mask = q_pos >= k_pos
        s = jnp.where(mask, s, NEG_INF)

        m_prev = lse_ref[0, 0, 0, :]
        l_prev = lse_ref[0, 0, 1, :]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        lse_ref[0, 0, 0, :] = m_new
        lse_ref[0, 0, 1, :] = l_prev * corr + p.sum(axis=1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, 0, :, :] = (
            o_ref[0, 0, :, :] * corr[:, None] + pv
        ).astype(o_ref.dtype)

    @pl.when(ki == nk - 1)
    def _finish():
        m = lse_ref[0, 0, 0, :]
        l = jnp.maximum(lse_ref[0, 0, 1, :], 1e-30)
        o_ref[0, 0, :, :] = (o_ref[0, 0, :, :] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, 0, :] = m + jnp.log(l)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, blk_q, blk_k
):
    """Grid (B, H, nq, nk): accumulate dq for one q block over kv blocks."""
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    @pl.when(ki * blk_k < (qi + 1) * blk_q)
    def _step():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, 0, :]  # (blk_q,)
        delta = delta_ref[0, 0, 0, :]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        q_pos = qi * blk_q + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0
        )
        k_pos = ki * blk_k + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dq_ref[0, 0, :, :] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, scale, blk_q, blk_k,
):
    """Grid (B, H, nk, nq): accumulate dk, dv for one kv block over q blocks."""
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    @pl.when((qi + 1) * blk_q > ki * blk_k)
    def _step():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, 0, :]
        delta = delta_ref[0, 0, 0, :]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        q_pos = qi * blk_q + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0
        )
        k_pos = ki * blk_k + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])  # (blk_q, blk_k)
        # dv += p^T @ do
        dv_ref[0, 0, :, :] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0, 0, :, :],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        # dk += ds^T @ q
        dk_ref[0, 0, :, :] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dk_ref.dtype)


def _block_sizes(S):
    if S % 128 != 0:
        raise ValueError(
            f"flash_attention requires seq len divisible by 128, got {S}; "
            "use the dense attention path for ragged lengths"
        )
    blk = 512 if S % 512 == 0 else (256 if S % 256 == 0 else 128)
    blk = min(blk, S)
    return blk, blk


def _interpret():
    return jax.devices()[0].platform != "tpu"


def _fwd(q, k, v, scale):
    """q, k, v: (B, H, S, D)."""
    B, H, S, D = q.shape
    blk_q, blk_k = _block_sizes(S)
    nq, nk = S // blk_q, S // blk_k
    grid = (B, H, nq, nk)
    qspec = pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0))
    kspec = pl.BlockSpec((1, 1, blk_k, D), lambda b, h, i, j: (b, h, j, 0))
    o, lse2 = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k),
        grid=grid,
        in_specs=[qspec, kspec, kspec],
        out_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0)),
            # rows: [m; l] during accumulation, [lse; l] after finish
            pl.BlockSpec((1, 1, 2, blk_q), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, 2, S), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse2[:, :, 0, :]


def _bwd(q, k, v, o, lse, do, scale):
    """All tensors (B, H, S, D); lse (B, H, S)."""
    B, H, S, D = q.shape
    blk_q, blk_k = _block_sizes(S)
    nq, nk = S // blk_q, S // blk_k
    delta = jnp.einsum(
        "bhsd,bhsd->bhs", do.astype(jnp.float32), o.astype(jnp.float32)
    )
    lse4 = lse[:, :, None, :]  # (B, H, 1, S)
    delta4 = delta[:, :, None, :]
    qspec = pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0))
    kspec = pl.BlockSpec((1, 1, blk_k, D), lambda b, h, i, j: (b, h, j, 0))
    rspec = pl.BlockSpec((1, 1, 1, blk_q), lambda b, h, i, j: (b, h, 0, i))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k),
        grid=(B, H, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rspec, rspec],
        out_specs=pl.BlockSpec(
            (1, 1, blk_q, D), lambda b, h, i, j: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse4, delta4)
    # For the dkv pass the grid iterates (kv, q): index maps swap i/j roles.
    qspec2 = pl.BlockSpec((1, 1, blk_q, D), lambda b, h, i, j: (b, h, j, 0))
    kspec2 = pl.BlockSpec((1, 1, blk_k, D), lambda b, h, i, j: (b, h, i, 0))
    rspec2 = pl.BlockSpec((1, 1, 1, blk_q), lambda b, h, i, j: (b, h, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k),
        grid=(B, H, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rspec2, rspec2],
        out_specs=[
            pl.BlockSpec((1, 1, blk_k, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, blk_k, D), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse4, delta4)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention_bhsd(q, k, v, scale: float | None = None):
    """Causal flash attention, (B, H, S, D) layout (kernel-native)."""
    o, _ = _fwd(q, k, v, scale or 1.0 / math.sqrt(q.shape[-1]))
    return o


def _flash_fwd(q, k, v, scale):
    s = scale or 1.0 / math.sqrt(q.shape[-1])
    o, lse = _fwd(q, k, v, s)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, res, do):
    q, k, v, o, lse = res
    s = scale or 1.0 / math.sqrt(q.shape[-1])
    return _bwd(q, k, v, o, lse, do, s)


flash_attention_bhsd.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, scale: float | None = None):
    """Causal flash attention.  q, k, v: (B, S, H, D) → (B, S, H, D).

    Thin layout adapter over :func:`flash_attention_bhsd`; the transposes
    fuse into neighboring ops under jit.  Models that can emit
    (B, H, S, D) directly should call the bhsd variant.
    """
    o = flash_attention_bhsd(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        scale,
    )
    return o.transpose(0, 2, 1, 3)


def sharded_flash_attention_bhsd(q, k, v, scale: float | None = None):
    """Flash attention that runs per-shard under an active mesh.

    pallas_call is a custom call XLA cannot auto-partition, so under pjit
    with a live mesh we shard_map over (batch → data axes, heads → tp) and
    run the kernel on the local block.  Sequence stays unsharded — sp
    sharding belongs to ring attention (ops/ring_attention.py).
    """
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import DATA_AXES, TP_AXIS

    mesh = None
    try:
        ambient = jax.sharding.get_mesh()
        if ambient is not None and not getattr(ambient, "empty", False):
            mesh = ambient
    except Exception:
        pass
    if mesh is None:
        from ray_tpu.parallel.mesh import current_mesh

        mesh = current_mesh()
    if mesh is None:
        return flash_attention_bhsd(q, k, v, scale)
    spec = P(DATA_AXES, TP_AXIS, None, None)
    fn = jax.shard_map(
        functools.partial(flash_attention_bhsd, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
