"""JaxTrainer: fit() a train_loop_per_worker across a TPU worker gang.

Role-equivalent of ray: python/ray/train/data_parallel_trainer.py:25
(DataParallelTrainer — training_loop:428) + base_trainer.py:567 (fit).
The reference routes fit() through a Tune trial; here the trainer runs
the gang directly and tune-lite wraps *it* (the layering inverted on
purpose — the SPMD gang is the primitive, HPO is a consumer).

Gang failure policy: any worker death restarts the WHOLE group from the
latest persisted checkpoint (FailureConfig.max_failures), matching SPMD
reality — a multi-host XLA program cannot lose one participant.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    TrainWorkerGroupError,
)
from ray_tpu.train.checkpoint import (
    _METRICS_FILE,
    Checkpoint,
    _ckpt_round,
    _read_metrics_sidecar,
)
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig


@dataclasses.dataclass
class Result:
    """Outcome of a run (ray: python/ray/air/result.py Result)."""

    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None
    error: Optional[BaseException] = None


class JaxTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict[str, Any]], Any],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend_config: Optional[BackendConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._train_fn = train_loop_per_worker
        self._config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or JaxConfig()
        self._resume_from = resume_from_checkpoint
        # Data ingest (reference: data_parallel_trainer.py:52-111
        # `datasets=` → per-worker streaming_split shards surfaced in the
        # loop via train.get_dataset_shard)
        self._datasets = dict(datasets or {})

    def fit(self) -> Result:
        failure = self.run_config.failure_config or FailureConfig()
        failures_left = failure.max_failures
        latest_checkpoint = self._resume_from
        history: List[Dict[str, Any]] = []
        last_metrics: Dict[str, Any] = {}
        executor = BackendExecutor(
            self.backend_config, self.scaling_config, self.run_config
        )
        while True:
            try:
                executor.start()
                executor.start_training(
                    self._train_fn, self._config, latest_checkpoint,
                    datasets=self._datasets,
                )
                while True:
                    reports = executor.next_reports()
                    if reports is None:
                        break
                    # rank 0's metrics are canonical (reference semantics)
                    last_metrics = reports[0]["metrics"]
                    last_metrics.setdefault("_timestamp", time.time())
                    history.append(dict(last_metrics))
                    # checkpoints were already persisted worker-side;
                    # just track the newest handle
                    ckpt = next(
                        (
                            r["checkpoint"]
                            for r in reports
                            if r["checkpoint"] is not None
                        ),
                        None,
                    )
                    if ckpt is not None:
                        latest_checkpoint = ckpt
                        self._prune_checkpoints(executor.trial_dir)
                executor.finish()
                executor.shutdown()
                return Result(
                    metrics=last_metrics,
                    checkpoint=latest_checkpoint,
                    path=executor.trial_dir,
                    metrics_dataframe=history,
                )
            except (TrainWorkerGroupError, TimeoutError) as e:
                # TimeoutError covers placement-group reservation failure;
                # the executor maps worker/get failures (incl. driver-side
                # get timeouts) to TrainWorkerGroupError.  Either way the
                # gang is torn down before deciding to retry or surface.
                executor.shutdown()
                # shutdown() returns before worker processes finish their
                # short exit grace, during which a survivor may still be
                # completing its final persist — wait for the trial dir
                # listing to go quiescent before rescanning.
                def _snapshot() -> Optional[List]:
                    # dir names AND their sidecar presence: a survivor's
                    # final act is the sidecar write inside an already-
                    # listed dir, which a name-only listing can't see
                    try:
                        td = executor.trial_dir
                        if not os.path.isdir(td):
                            return []
                        return sorted(
                            (
                                d,
                                os.path.exists(
                                    os.path.join(td, d, _METRICS_FILE)
                                ),
                            )
                            for d in os.listdir(td)
                        )
                    except OSError:
                        return None

                prev = None
                for _ in range(8):
                    cur = _snapshot()
                    if cur is None or cur == prev:
                        break
                    prev = cur
                    time.sleep(0.25)
                # Workers persist checkpoints before report() returns, so
                # storage may be ahead of the last handle the driver saw —
                # rescan and take the newest.  When it IS ahead, also adopt
                # its metrics sidecar so metrics match the checkpoint: this
                # holds for BOTH the retry (the resumed loop starts past
                # that step and may report nothing new) and the terminal
                # Result below (its checkpoint must be the newest too).
                rescanned = self._latest_persisted(executor.trial_dir)
                if rescanned is not None:
                    # `seen` counts only checkpoints of THIS trial: a
                    # resume_from_checkpoint handle into some other run's
                    # dir may parse to an arbitrary round and must not
                    # suppress sidecar adoption here.
                    seen = None
                    if latest_checkpoint is not None and os.path.realpath(
                        os.path.dirname(latest_checkpoint.path)
                    ) == os.path.realpath(executor.trial_dir):
                        seen = _ckpt_round(latest_checkpoint.path)
                    found = _ckpt_round(rescanned.path)
                    if found is not None and (seen is None or found > seen):
                        side = _read_metrics_sidecar(rescanned.path)
                        if side is not None:
                            last_metrics = side
                            last_metrics.setdefault(
                                "_timestamp", time.time()
                            )
                            history.append(dict(last_metrics))
                    # Never move the resume point backwards OR sideways:
                    # the verified-round fallback can return an older
                    # round than the driver consumed (newest sidecar write
                    # failed), and at equal rounds the rescan may have
                    # picked a different rank's partial dir — the driver's
                    # known-good handle wins unless storage is strictly
                    # newer.
                    if seen is None or (found is not None and found > seen):
                        latest_checkpoint = rescanned
                if failures_left == 0:
                    return Result(
                        metrics=last_metrics,
                        checkpoint=latest_checkpoint,
                        path=executor.trial_dir,
                        metrics_dataframe=history,
                        error=e,
                    )
                if failures_left > 0:
                    failures_left -= 1

    def _latest_persisted(self, trial_dir: str) -> Optional[Checkpoint]:
        if not os.path.isdir(trial_dir):
            return None
        ckpts = sorted(
            d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")
        )
        if not ckpts:
            return None
        rounds = [_ckpt_round(d) for d in ckpts]
        top = max((r for r in rounds if r is not None), default=None)
        if top is None:
            return Checkpoint(os.path.join(trial_dir, ckpts[-1]))
        # Newest VERIFIED round wins: the metrics sidecar is written after
        # persist() completes, so it marks a directory as fully persisted
        # (a rank that died mid-persist leaves none).  A sole partial dir
        # in the top round must not shadow a complete earlier round, so
        # fall back across rounds to the newest one holding a verified
        # dir; if no round has any sidecar (pre-sidecar dirs, write
        # failures), take the newest round as-is.  Within a round prefer
        # verified dirs, then the LOWEST rank (rank 0's metrics are
        # canonical; same-round dirs sort by rank).
        def verified(d: str) -> bool:
            return os.path.exists(os.path.join(trial_dir, d, _METRICS_FILE))

        by_round: Dict[int, List[str]] = {}
        for d, r in zip(ckpts, rounds):
            if r is not None:
                by_round.setdefault(r, []).append(d)
        pick_round = top
        for r in sorted(by_round, reverse=True):
            if any(verified(d) for d in by_round[r]):
                pick_round = r
                break
        cands = sorted(
            by_round[pick_round], key=lambda d: (0 if verified(d) else 1, d)
        )
        return Checkpoint(os.path.join(trial_dir, cands[0]))

    def _prune_checkpoints(self, trial_dir: str):
        import shutil

        cc = self.run_config.checkpoint_config
        if cc is None or cc.num_to_keep is None:
            return
        ckpts = sorted(
            d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")
        )
        for stale in ckpts[: -cc.num_to_keep]:
            shutil.rmtree(os.path.join(trial_dir, stale), ignore_errors=True)
